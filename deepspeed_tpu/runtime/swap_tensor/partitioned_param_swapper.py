"""NVMe residency for ZeRO-3 parameter partitions.

Reference analog: ``AsyncPartitionedParameterSwapper``
(runtime/swap_tensor/partitioned_param_swapper.py:36) — each rank's shard of
each parameter can live on fast storage instead of HBM/host RAM; shards are
prefetched (async read into pooled buffers) ahead of use and released (or
written back) after.  The reference tracks status on the torch Parameter
(``ds_tensor.status``); here the swapper owns the status map keyed by param
name, and the engine's host-offload path asks for shards around each
sub-group optimizer step.
"""

from __future__ import annotations

import os
from enum import Enum
from typing import Dict, Iterable, List, Optional

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper
from deepspeed_tpu.runtime.swap_tensor.buffer_pool import SwapBufferPool


class PartitionedParamStatus(Enum):
    AVAILABLE = 1      # shard resident in host memory
    NOT_AVAILABLE = 2  # shard on storage only
    INFLIGHT = 3       # read submitted, not yet complete


class AsyncPartitionedParameterSwapper:
    def __init__(self, swap_folder: str, buffer_count: int = 5,
                 buffer_size: int = int(1e8), aio_handle=None):
        self.swapper = AsyncTensorSwapper(os.path.join(swap_folder, "params"),
                                          aio_handle=aio_handle)
        self.pool = SwapBufferPool(buffer_size, buffer_count)
        self.status: Dict[str, PartitionedParamStatus] = {}
        self._resident: Dict[str, np.ndarray] = {}
        self._pooled: Dict[str, bool] = {}

    # -- write path -------------------------------------------------------
    def swap_out_and_release(self, name: str, shard: np.ndarray,
                             async_op: bool = True) -> None:
        """Persist a shard and drop host residency (reference
        swap_out_and_release)."""
        self.swapper.swap_out(name, shard, async_op=async_op)
        if not async_op:
            self._drop(name)
        # async release happens at synchronize_writes()
        self.status[name] = PartitionedParamStatus.NOT_AVAILABLE

    def synchronize_writes(self) -> None:
        self.swapper.synchronize()
        for name, st in list(self.status.items()):
            if st == PartitionedParamStatus.NOT_AVAILABLE:
                self._drop(name)

    # -- read path --------------------------------------------------------
    def swap_in(self, names: Iterable[str], async_op: bool = True) -> None:
        """Submit reads for shards (prefetch when async)."""
        for name in names:
            if self.status.get(name) in (PartitionedParamStatus.AVAILABLE,
                                         PartitionedParamStatus.INFLIGHT):
                continue
            self._drop(name)  # recycle any stale resident buffer first
            shape, dtype = self.swapper.meta(name)
            nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
            buf = self.pool.get(nbytes)
            pooled = buf is not None
            out = buf.view(dtype).reshape(shape) if pooled else None
            self.swapper.swap_in(name, async_op=True, out=out)
            self.status[name] = PartitionedParamStatus.INFLIGHT
            self._pooled[name] = pooled
        if not async_op:
            self.synchronize_reads()

    def synchronize_reads(self) -> None:
        for name in list(self.status):
            self._complete_inflight(name)

    def get(self, name: str) -> np.ndarray:
        """Host array for an AVAILABLE shard (blocks if inflight)."""
        self._complete_inflight(name)
        assert self.status.get(name) == PartitionedParamStatus.AVAILABLE, \
            f"shard '{name}' is not resident (status={self.status.get(name)})"
        return self._resident[name]

    def release(self, name: str) -> None:
        """Drop host residency without touching storage."""
        self._complete_inflight(name)
        self._drop(name)
        if name in self.swapper._meta:
            self.status[name] = PartitionedParamStatus.NOT_AVAILABLE

    def remove(self, name: str) -> None:
        """Forget the shard entirely (storage + host)."""
        self._complete_inflight(name)
        self._drop(name)
        self.swapper.release(name)
        self.status.pop(name, None)

    def _complete_inflight(self, name: str) -> None:
        """An INFLIGHT read must finish before its buffer can be recycled."""
        if self.status.get(name) == PartitionedParamStatus.INFLIGHT:
            self._resident[name] = self.swapper.wait_in(name)
            self.status[name] = PartitionedParamStatus.AVAILABLE

    def available_swap_in_buffers(self) -> int:
        return self.pool.available()

    def _drop(self, name: str) -> None:
        arr = self._resident.pop(name, None)
        if arr is not None and self._pooled.pop(name, False):
            base = arr.view(np.uint8).reshape(-1)
            self.pool.put(base)
