"""Reusable host swap buffers.

Analog of the reference's pinned-buffer pool
(csrc/aio/py_lib/deepspeed_pin_tensor.cpp + runtime/swap_tensor/utils.py
SwapBufferPool/SwapBufferManager): fixed-count, fixed-size aligned numpy
buffers recycled across swap operations so steady-state swapping does no
allocation.  On TPU hosts there is no cudaHostRegister; page-aligned numpy
memory is what the dma/IO path wants.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

ALIGNMENT = 4096  # O_DIRECT-friendly


def aligned_empty(nbytes: int, dtype=np.uint8) -> np.ndarray:
    """Allocate a page-aligned 1-D buffer of at least nbytes."""
    pad = ALIGNMENT
    raw = np.empty(nbytes + pad, dtype=np.uint8)
    off = (-raw.ctypes.data) % ALIGNMENT
    return raw[off:off + nbytes].view(dtype)


class SwapBufferPool:
    """count × size pool with checkout/checkin semantics (reference
    SwapBufferManager, runtime/swap_tensor/utils.py:115)."""

    def __init__(self, buffer_size_bytes: int, count: int):
        self.buffer_size = int(buffer_size_bytes)
        self._free: List[np.ndarray] = [aligned_empty(self.buffer_size)
                                        for _ in range(count)]
        self._used: Dict[int, np.ndarray] = {}

    def available(self) -> int:
        return len(self._free)

    def get(self, nbytes: int) -> Optional[np.ndarray]:
        """Checkout a buffer view of exactly nbytes (None if exhausted or
        oversized — caller falls back to a one-off allocation)."""
        if nbytes > self.buffer_size or not self._free:
            return None
        buf = self._free.pop()
        self._used[buf.ctypes.data] = buf
        return buf[:nbytes]

    def put(self, view: np.ndarray) -> None:
        # checked-out views are prefix slices, so the view's data pointer is
        # the pool buffer's start address regardless of dtype reshapes
        buf = self._used.pop(view.ctypes.data, None)
        if buf is not None:
            self._free.append(buf)
