"""Checkpoint engine interface — analog of reference
``runtime/checkpoint_engine/checkpoint_engine.py:9`` (CheckpointEngine ABC)
with Torch/Nebula engines replaced by Native (npz) and Orbax backends.
"""

from __future__ import annotations

import abc
import json
import os
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import log_dist


class CheckpointEngine(abc.ABC):
    def __init__(self, config_params=None):
        self.config = config_params

    def create(self, tag: str):
        """Notify start of a checkpoint under ``tag`` (reference create())."""

    @abc.abstractmethod
    def save(self, state_dict: Dict[str, Any], path: str, on_success=None):
        """Persist ``state_dict``. ``on_success`` (if given) runs exactly
        once after the state is durably written — sidecar finalization like
        the 'latest' pointer belongs there so a failed async write can never
        publish a broken checkpoint."""
        ...

    @abc.abstractmethod
    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        ...

    def commit(self, tag: str) -> bool:
        """Flush / finalize ``tag`` (reference commit())."""
        return True

    def makedirs(self, path, exist_ok=True):
        os.makedirs(path, exist_ok=exist_ok)


def _to_global_numpy(leaf) -> np.ndarray:
    """Fetch a (possibly multi-host-sharded) array as a full numpy array.
    Under multi-host, shards on non-addressable devices require a gather
    (process_allgather); single-host arrays are device_get directly."""
    import jax

    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten_state(tree, prefix="") -> Dict[str, np.ndarray]:
    """Flatten a pytree into path-keyed numpy arrays ('a/b/0/c' keys)."""
    import jax

    flat = {}
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_path:
        key = "/".join(_path_entry_str(p) for p in path)
        flat[prefix + key] = _to_global_numpy(leaf)
    return flat


def _path_entry_str(entry) -> str:
    import jax

    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return str(entry.name)
    if isinstance(entry, jax.tree_util.FlattenedIndexKey):
        return str(entry.key)
    return str(entry)


def _unflatten_into(tree_like, flat: Dict[str, np.ndarray], strict: bool = True):
    """Rebuild arrays matching ``tree_like``'s structure from path-keyed dict."""
    import jax

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree_like)
    out = []
    missing = []
    for path, leaf in leaves_with_path:
        key = "/".join(_path_entry_str(p) for p in path)
        if key in flat:
            arr = flat[key]
            out.append(arr)
        else:
            missing.append(key)
            out.append(np.asarray(jax.device_get(leaf)))
    if missing and strict:
        raise KeyError(f"checkpoint missing keys: {missing[:10]}"
                       f"{'...' if len(missing) > 10 else ''}")
    return jax.tree_util.tree_unflatten(treedef, out), missing


class NativeCheckpointEngine(CheckpointEngine):
    """npz-based global-array checkpoints: one logical checkpoint keyed by
    parameter path, independent of mesh/ZeRO layout — "universal by default"
    (the reference needs a whole conversion subsystem, deepspeed/checkpoint/,
    to get this property; see SURVEY §5.4)."""

    def save(self, state_dict: Dict[str, Any], path: str, on_success=None):
        import jax
        import ml_dtypes

        self.makedirs(os.path.dirname(path))
        arrays = {}
        meta = {}
        for section, tree in state_dict.items():
            if section == "__meta__":
                meta = tree
                continue
            arrays.update(_flatten_state(tree, prefix=f"{section}::"))
        # npz round-trips 16-bit floats as raw void — store as uint16 views
        out = {}
        for k, v in arrays.items():
            if v.dtype == ml_dtypes.bfloat16:
                out[k + "@bf16"] = v.view(np.uint16)
            elif v.dtype == np.float16:
                out[k + "@f16"] = v.view(np.uint16)
            else:
                out[k] = v
        if jax.process_index() == 0:  # gather above is collective; write once
            np.savez(path, __meta__=json.dumps(meta), **out)
        log_dist(f"[native-ckpt] saved {len(arrays)} arrays to {path}", ranks=[0])
        if on_success is not None:
            on_success()

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        import ml_dtypes

        if not os.path.exists(path):
            raise FileNotFoundError(path)
        data = np.load(path, allow_pickle=False)
        out: Dict[str, Dict[str, np.ndarray]] = {}
        meta = {}
        for key in data.files:
            if key == "__meta__":
                meta = json.loads(str(data[key]))
                continue
            arr = data[key]
            if key.endswith("@bf16"):
                key, arr = key[:-5], arr.view(ml_dtypes.bfloat16)
            elif key.endswith("@f16"):
                key, arr = key[:-4], arr.view(np.float16)
            section, sub = key.split("::", 1)
            out.setdefault(section, {})[sub] = arr
        out["__meta__"] = meta
        return out


class AsyncCheckpointEngine(CheckpointEngine):
    """Background-thread persistence — the Nebula analog
    (reference NebulaCheckpointEngine, runtime/checkpoint_engine/
    nebula_checkpoint_engine.py: save returns immediately, an external
    service persists, ``commit(tag)`` finalizes).

    ``save`` snapshots the state to host memory synchronously — deep copies,
    so training may mutate params/host-optimizer state immediately after —
    and hands the file write (plus the caller's ``on_success`` finalizer,
    e.g. the 'latest' pointer) to a worker thread.  A new ``save`` first
    joins the previous write (double-buffering: write N overlaps training
    toward N+1), which is also where a prior write's error surfaces.
    ``commit`` is non-blocking; ``wait`` joins everything explicitly."""

    def __init__(self, config_params=None, inner: Optional[CheckpointEngine] = None):
        super().__init__(config_params)
        self.inner = inner or NativeCheckpointEngine(config_params)
        self._pending: list = []
        self._errors: list = []

    def save(self, state_dict: Dict[str, Any], path: str, on_success=None):
        import threading

        self.wait()  # join the previous write; surfaces its errors
        # synchronous device→host snapshot with DEEP COPIES: numpy leaves
        # (host-offload masters/moments) are mutated in place by the next
        # optimizer step, and device_get can alias buffers on the CPU backend
        snapshot: Dict[str, Any] = {}
        for section, tree in state_dict.items():
            if section == "__meta__":
                snapshot[section] = dict(tree)
            else:
                snapshot[section] = {k: np.array(v, copy=True)
                                     for k, v in _flatten_state(tree).items()}

        def write():
            try:
                # pre-flattened sections pass through _flatten_state unchanged
                self.inner.save(snapshot, path, on_success=on_success)
            except Exception as e:  # surfaced at the next save()/wait()/load()
                self._errors.append(e)

        # non-daemon: the interpreter joins outstanding writes at exit, so a
        # save issued as the script's last act is never silently truncated
        t = threading.Thread(target=write, daemon=False)
        t.start()
        self._pending.append(t)

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        self.wait()
        return self.inner.load(path, map_location)

    def commit(self, tag: str) -> bool:
        # non-blocking: durability is finalized by the worker (on_success);
        # errors surface on the next save()/wait()/load()
        return True

    def wait(self):
        for t in self._pending:
            t.join()
        self._pending.clear()
        if self._errors:
            err = self._errors[0]
            self._errors.clear()
            raise RuntimeError(f"async checkpoint write failed: {err}") from err


class OrbaxCheckpointEngine(CheckpointEngine):
    """Orbax-backed engine for multi-host distributed saving (the Nebula
    analog: reference NebulaCheckpointEngine delegates persistence to an
    external service; orbax plays that role here). Synchronous
    StandardCheckpointer for now. Select via
    ``save/load_engine_checkpoint(..., checkpoint_engine=...)``."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()

    def save(self, state_dict: Dict[str, Any], path: str, on_success=None):
        state_dict = dict(state_dict)  # don't mutate the caller's dict
        meta = state_dict.pop("__meta__", {})
        self._ckptr.save(os.path.abspath(path) + ".orbax", state_dict, force=True)
        with open(path + ".meta.json", "w") as f:
            json.dump(meta, f)
        if on_success is not None:
            on_success()

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        out = self._ckptr.restore(os.path.abspath(path) + ".orbax")
        try:
            with open(path + ".meta.json") as f:
                out["__meta__"] = json.load(f)
        except FileNotFoundError:
            out["__meta__"] = {}
        return out
