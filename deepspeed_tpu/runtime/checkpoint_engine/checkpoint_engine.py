"""Checkpoint engine interface — analog of reference
``runtime/checkpoint_engine/checkpoint_engine.py:9`` (CheckpointEngine ABC)
with Torch/Nebula engines replaced by Native (npz) and Orbax backends.
"""

from __future__ import annotations

import abc
import copy
import json
import os
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils import fs
from deepspeed_tpu.utils.logging import log_dist, logger

MANIFEST_KEY = "__integrity__"
MANIFEST_VERSION = 1


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity verification (truncated file, checksum
    mismatch, missing/extra arrays, or absent manifest where required)."""


class CheckpointEngine(abc.ABC):
    def __init__(self, config_params=None):
        self.config = config_params

    def create(self, tag: str):
        """Notify start of a checkpoint under ``tag`` (reference create())."""

    @abc.abstractmethod
    def save(self, state_dict: Dict[str, Any], path: str, on_success=None):
        """Persist ``state_dict``. ``on_success`` (if given) runs exactly
        once after the state is durably written — sidecar finalization like
        the 'latest' pointer belongs there so a failed async write can never
        publish a broken checkpoint."""
        ...

    @abc.abstractmethod
    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        ...

    def commit(self, tag: str) -> bool:
        """Flush / finalize ``tag`` (reference commit())."""
        return True

    def makedirs(self, path, exist_ok=True):
        os.makedirs(path, exist_ok=exist_ok)


def _to_global_numpy(leaf) -> np.ndarray:
    """Fetch a (possibly multi-host-sharded) array as a full numpy array.
    Under multi-host, shards on non-addressable devices require a gather
    (process_allgather); single-host arrays are device_get directly."""
    import jax

    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten_state(tree, prefix="") -> Dict[str, np.ndarray]:
    """Flatten a pytree into path-keyed numpy arrays ('a/b/0/c' keys)."""
    import jax

    flat = {}
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_path:
        key = "/".join(_path_entry_str(p) for p in path)
        flat[prefix + key] = _to_global_numpy(leaf)
    return flat


def _path_entry_str(entry) -> str:
    import jax

    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return str(entry.name)
    if isinstance(entry, jax.tree_util.FlattenedIndexKey):
        return str(entry.key)
    return str(entry)


def _unflatten_into(tree_like, flat: Dict[str, np.ndarray], strict: bool = True):
    """Rebuild arrays matching ``tree_like``'s structure from path-keyed dict."""
    import jax

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree_like)
    out = []
    missing = []
    for path, leaf in leaves_with_path:
        key = "/".join(_path_entry_str(p) for p in path)
        if key in flat:
            arr = flat[key]
            out.append(arr)
        else:
            missing.append(key)
            out.append(np.asarray(jax.device_get(leaf)))
    if missing and strict:
        raise KeyError(f"checkpoint missing keys: {missing[:10]}"
                       f"{'...' if len(missing) > 10 else ''}")
    return jax.tree_util.tree_unflatten(treedef, out), missing


def _array_checksum(arr: np.ndarray) -> Dict[str, Any]:
    """Per-array integrity record over the *stored* representation."""
    return {"crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF,
            "dtype": str(arr.dtype),
            "shape": list(arr.shape)}


def _build_manifest(stored: Dict[str, np.ndarray]) -> Dict[str, Any]:
    return {"version": MANIFEST_VERSION,
            "arrays": {k: _array_checksum(v) for k, v in stored.items()}}


def _verify_manifest(manifest: Dict[str, Any],
                     stored: Dict[str, np.ndarray]) -> Tuple[bool, str]:
    """Check ``stored`` arrays against ``manifest``; returns (ok, reason)."""
    expected = manifest.get("arrays", {})
    missing = sorted(set(expected) - set(stored))
    extra = sorted(set(stored) - set(expected))
    if missing or extra:
        return False, (f"array set mismatch (missing {missing[:5]}, "
                       f"unexpected {extra[:5]})")
    bad = []
    for key, rec in expected.items():
        got = _array_checksum(stored[key])
        if got != rec:
            bad.append(f"{key} (expected {rec}, got {got})")
    if bad:
        return False, f"checksum mismatch: {'; '.join(bad[:3])}"
    return True, "ok"


def verify_checkpoint(path: str, require_manifest: bool = True) -> Tuple[bool, str]:
    """Standalone integrity check of a native ``state.npz``: readable zip,
    manifest present, every array's crc32/dtype/shape matches. Never raises —
    returns ``(ok, reason)`` so auto-resume can log *why* a tag was skipped."""
    if not os.path.exists(path):
        return False, "missing state file"
    try:
        data = fs.retry_io(lambda: np.load(path, allow_pickle=False),
                           description=f"open {path}")
        stored = {k: data[k] for k in data.files if k != "__meta__"}
        meta = json.loads(str(data["__meta__"])) if "__meta__" in data.files else {}
    except Exception as e:  # truncated zip, bad header, I/O error, ...
        return False, f"unreadable ({type(e).__name__}: {e})"
    manifest = meta.get(MANIFEST_KEY)
    if manifest is None:
        if require_manifest:
            return False, "no integrity manifest"
        return True, "ok (no manifest; unverified)"
    return _verify_manifest(manifest, stored)


class NativeCheckpointEngine(CheckpointEngine):
    """npz-based global-array checkpoints: one logical checkpoint keyed by
    parameter path, independent of mesh/ZeRO layout — "universal by default"
    (the reference needs a whole conversion subsystem, deepspeed/checkpoint/,
    to get this property; see SURVEY §5.4).

    Durability contract: the npz is serialized in memory, written to
    ``path + '.tmp'`` with retries, and atomically renamed onto ``path`` —
    a crash mid-save never leaves a torn file at the final name. Every
    stored array's crc32/dtype/shape is recorded in a manifest inside
    ``__meta__`` and verified on load."""

    def save(self, state_dict: Dict[str, Any], path: str, on_success=None):
        import jax
        import ml_dtypes

        dirname = os.path.dirname(path)
        if dirname:  # bare filename → cwd; os.makedirs("") would raise
            self.makedirs(dirname)
        arrays = {}
        meta = {}
        for section, tree in state_dict.items():
            if section == "__meta__":
                meta = tree
                continue
            arrays.update(_flatten_state(tree, prefix=f"{section}::"))
        # npz round-trips 16-bit floats as raw void — store as uint16 views
        out = {}
        for k, v in arrays.items():
            if v.dtype == ml_dtypes.bfloat16:
                out[k + "@bf16"] = v.view(np.uint16)
            elif v.dtype == np.float16:
                out[k + "@f16"] = v.view(np.uint16)
            else:
                out[k] = v
        if jax.process_index() == 0:  # gather above is collective; write once
            meta = dict(meta)  # don't mutate the caller's meta
            # manifest only on the writing process: checksumming the whole
            # gathered state on every non-writing host would be pure waste
            meta[MANIFEST_KEY] = _build_manifest(out)
            # streamed: the serialized zip never exists in host memory —
            # at multi-GB scale the gathered arrays alone are the budget
            fs.atomic_stream_write(
                path, lambda f: np.savez(f, __meta__=json.dumps(meta), **out))
        log_dist(f"[native-ckpt] saved {len(arrays)} arrays to {path}", ranks=[0])
        if on_success is not None:
            on_success()

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        import ml_dtypes

        if not os.path.exists(path):
            raise FileNotFoundError(path)
        try:
            data = fs.retry_io(lambda: np.load(path, allow_pickle=False),
                               description=f"open {path}")
            files = list(data.files)
            out: Dict[str, Dict[str, np.ndarray]] = {}
            meta = {}
            stored: Dict[str, np.ndarray] = {}
            for key in files:
                if key == "__meta__":
                    meta = json.loads(str(data[key]))
                    continue
                stored[key] = data[key]
        except Exception as e:
            raise CheckpointCorruptionError(
                f"checkpoint {path} is unreadable "
                f"({type(e).__name__}: {e}) — likely a truncated or torn write"
            ) from e
        manifest = meta.get(MANIFEST_KEY)
        if manifest is None:
            logger.warning(f"checkpoint {path} has no integrity manifest; "
                           f"loading unverified (pre-manifest checkpoint?)")
        else:
            ok, reason = _verify_manifest(manifest, stored)
            if not ok:
                raise CheckpointCorruptionError(
                    f"checkpoint {path} failed integrity verification: {reason}")
        for key, arr in stored.items():
            if key.endswith("@bf16"):
                key, arr = key[:-5], arr.view(ml_dtypes.bfloat16)
            elif key.endswith("@f16"):
                key, arr = key[:-4], arr.view(np.float16)
            section, sub = key.split("::", 1)
            out.setdefault(section, {})[sub] = arr
        out["__meta__"] = meta
        return out


class AsyncCheckpointEngine(CheckpointEngine):
    """Background-thread persistence — the Nebula analog
    (reference NebulaCheckpointEngine, runtime/checkpoint_engine/
    nebula_checkpoint_engine.py: save returns immediately, an external
    service persists, ``commit(tag)`` finalizes).

    ``save`` snapshots the state to host memory synchronously — deep copies,
    so training may mutate params/host-optimizer state immediately after —
    and hands the file write (plus the caller's ``on_success`` finalizer,
    e.g. the 'latest' pointer) to a worker thread.  A new ``save`` first
    joins the previous write (double-buffering: write N overlaps training
    toward N+1), which is also where a prior write's error surfaces.
    ``commit`` is non-blocking; ``wait`` joins everything explicitly."""

    def __init__(self, config_params=None, inner: Optional[CheckpointEngine] = None):
        super().__init__(config_params)
        self.inner = inner or NativeCheckpointEngine(config_params)
        self._pending: list = []
        self._errors: list = []

    def save(self, state_dict: Dict[str, Any], path: str, on_success=None):
        import threading

        self.wait()  # join the previous write; surfaces its errors
        # synchronous device→host snapshot with DEEP COPIES: numpy leaves
        # (host-offload masters/moments) are mutated in place by the next
        # optimizer step, and device_get can alias buffers on the CPU backend
        snapshot: Dict[str, Any] = {}
        for section, tree in state_dict.items():
            if section == "__meta__":
                # deep copy: a shallow dict() would alias nested dicts that
                # the caller mutates during the overlapped write
                snapshot[section] = copy.deepcopy(tree)
            else:
                snapshot[section] = {k: np.array(v, copy=True)
                                     for k, v in _flatten_state(tree).items()}

        def write():
            try:
                # pre-flattened sections pass through _flatten_state unchanged
                self.inner.save(snapshot, path, on_success=on_success)
            except Exception as e:  # surfaced at the next save()/wait()/load()
                self._errors.append(e)

        # non-daemon: the interpreter joins outstanding writes at exit, so a
        # save issued as the script's last act is never silently truncated
        t = threading.Thread(target=write, daemon=False)
        t.start()
        self._pending.append(t)

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        self.wait()
        return self.inner.load(path, map_location)

    def commit(self, tag: str) -> bool:
        # non-blocking: durability is finalized by the worker (on_success);
        # errors surface on the next save()/wait()/load()
        return True

    def wait(self):
        for t in self._pending:
            t.join()
        self._pending.clear()
        if self._errors:
            errs = list(self._errors)
            self._errors.clear()
            detail = "; ".join(f"{type(e).__name__}: {e}" for e in errs)
            raise RuntimeError(
                f"async checkpoint write failed ({len(errs)} error"
                f"{'s' if len(errs) != 1 else ''}): {detail}") from errs[0]


class OrbaxCheckpointEngine(CheckpointEngine):
    """Orbax-backed engine for multi-host distributed saving (the Nebula
    analog: reference NebulaCheckpointEngine delegates persistence to an
    external service; orbax plays that role here). Synchronous
    StandardCheckpointer for now. Select via
    ``save/load_engine_checkpoint(..., checkpoint_engine=...)``."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()

    def save(self, state_dict: Dict[str, Any], path: str, on_success=None):
        state_dict = dict(state_dict)  # don't mutate the caller's dict
        meta = state_dict.pop("__meta__", {})
        self._ckptr.save(os.path.abspath(path) + ".orbax", state_dict, force=True)
        fs.atomic_write_text(path + ".meta.json", json.dumps(meta))
        if on_success is not None:
            on_success()

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        out = self._ckptr.restore(os.path.abspath(path) + ".orbax")
        try:
            with open(path + ".meta.json") as f:
                out["__meta__"] = json.load(f)
        except FileNotFoundError:
            out["__meta__"] = {}
        return out
