"""Engine checkpoint save/load — analog of reference engine checkpoint logic
(engine.py save_checkpoint:2792 / load_checkpoint:2487 / _save_zero_checkpoint
:3136 / save_16bit_model:3213 / _zero3_consolidated_16bit_state_dict:3146)
plus the universal-checkpoint property of ``deepspeed/checkpoint/`` for free.

Layout under ``save_dir``:
    latest                       — text file holding the newest tag
    <tag>/state.npz              — global param/optimizer/scaler arrays (path-keyed)
    <tag>/client_state.json      — counters, lr-scheduler state, user state
Checkpoints carry *global* (unsharded) arrays keyed by parameter path, so a
load under ANY mesh/ZeRO-stage re-sharding is just device_put with the new
plan's shardings — dp/tp resize needs no conversion pass.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (
    MANIFEST_KEY,
    CheckpointCorruptionError,
    NativeCheckpointEngine,
    _flatten_state,
    _unflatten_into,
    verify_checkpoint,
)
from deepspeed_tpu.telemetry import record_event
from deepspeed_tpu.utils import fs
from deepspeed_tpu.utils.logging import log_dist, logger


def _tag_for(engine, tag: Optional[str]) -> str:
    return tag if tag is not None else f"global_step{engine.global_steps}"


def _validate_tag(engine, tag: str):
    """Tag consistency across processes (reference _checkpoint_tag_validation
    :2775): all hosts must agree on the tag or resume desyncs."""
    mode = engine.config.checkpoint_config.tag_validation
    if mode == "Ignore" or jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    try:
        multihost_utils.assert_equal(np.frombuffer(
            tag.encode().ljust(64)[:64], dtype=np.uint8), f"checkpoint tag mismatch: {tag}")
    except Exception as e:
        if mode == "Fail":
            raise
        logger.warning(f"checkpoint tag validation: {e}")


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                           client_state: Optional[dict] = None, save_latest: bool = True,
                           checkpoint_engine=None):
    tag = _tag_for(engine, tag)
    _validate_tag(engine, tag)
    ckpt_engine = checkpoint_engine or NativeCheckpointEngine()
    ckpt_engine.create(tag)
    os.makedirs(os.path.join(save_dir, tag), exist_ok=True)  # before any
    # sync sidecar writes: an async engine creates it only in its worker
    path = os.path.join(save_dir, tag, "state.npz")
    state = engine.state
    state_dict = {
        "params": state.params,
        "opt_state": state.opt_state,
        "scaler": state.scaler,
        "__meta__": {"global_step": int(jax.device_get(state.global_step))},
    }
    host_opt = getattr(engine, "_host_opt", None)
    if host_opt is not None:
        # ZeRO-Offload: the authoritative fp32 masters + moments are host-side
        hsd = host_opt.state_dict()
        state_dict["host_opt"] = hsd["state"]
        state_dict["__meta__"]["host_opt_step"] = hsd["step"]
    # deterministic data-pipeline resume (ISSUE 10): the (seed, epoch,
    # in-epoch offset) triple rides in __meta__ so a rewound or restarted
    # run replays exactly the batch stream an uninterrupted run would see.
    # Not persisted for external data_samplers — their order may not
    # replay across a restart, and a position we can't honor is worse
    # than none.
    dataloader = getattr(engine, "training_dataloader", None)
    if dataloader is not None and hasattr(dataloader, "state_dict") and \
            getattr(dataloader, "supports_deterministic_resume",
                    lambda: True)():
        state_dict["__meta__"]["dataloader"] = dataloader.state_dict()

    cs = {
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "zero_stage": engine.zero_stage,
        "dtype": str(engine.compute_dtype.__name__),
        "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler else None,
        "client_state": client_state or {},
        "mesh_shape": list(engine.topology.mesh_shape),
    }

    def finalize():
        """Runs only after the state is durably written — an async engine
        must never publish 'latest' for a failed write. Both sidecars are
        published atomically (tmp + rename) so a crash here can't leave a
        torn 'latest' pointing nowhere or a half-written client state."""
        if jax.process_index() == 0:
            fs.atomic_write_text(os.path.join(save_dir, tag, "client_state.json"),
                                 json.dumps(cs, indent=2))
            if save_latest:
                fs.atomic_write_text(os.path.join(save_dir, "latest"), tag)

    ckpt_engine.save(state_dict, path, on_success=finalize)
    ckpt_engine.commit(tag)
    record_event("checkpoint/saves", tag=tag,
                 global_step=cs["global_steps"])
    log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])
    return True


def list_checkpoint_tags(load_dir: str):
    """Tag directories under ``load_dir`` that look like checkpoints (native
    npz or orbax layout), newest state file first."""
    try:
        names = os.listdir(load_dir)
    except OSError:
        return []
    found = []
    for name in names:
        tag_dir = os.path.join(load_dir, name)
        if not os.path.isdir(tag_dir):
            continue
        for probe in ("state.npz", "state.npz.orbax", "state.npz.meta.json"):
            p = os.path.join(tag_dir, probe)
            try:
                found.append((os.path.getmtime(p), name))
                break
            except OSError:  # vanished between listdir and stat (cleanup race)
                continue
    return [name for _, name in sorted(found, reverse=True)]


def validate_checkpoint_tag(load_dir: str, tag: str):
    """Cheap structural + integrity validation of one tag; (ok, reason).
    Native checkpoints must carry a manifest with passing checksums; orbax
    checkpoints (self-verified by orbax) just need their directory."""
    npz = os.path.join(load_dir, tag, "state.npz")
    if os.path.exists(npz):
        return verify_checkpoint(npz, require_manifest=True)
    if os.path.exists(npz + ".orbax"):
        return True, "ok (orbax, self-verified)"
    return False, "missing state.npz"


_NO_MANIFEST = "no integrity manifest"


def _read_client_state(load_dir: str, tag: str):
    """Parse a tag's client_state.json; None when absent or unreadable.
    Explicit-tag loads resume from checkpoint meta alone when the sidecar
    is torn (pre-atomic writer) — the state itself loaded fine."""
    cs_path = os.path.join(load_dir, tag, "client_state.json")
    if not os.path.exists(cs_path):
        return None
    try:
        return json.loads(fs.read_bytes_with_retry(cs_path).decode())
    except Exception as e:
        logger.warning(f"client_state.json for tag '{tag}' unreadable "
                       f"({type(e).__name__}: {e}); resuming from "
                       f"checkpoint meta only")
        return None


def _read_latest_tag(load_dir: str):
    """Best-effort read of the 'latest' pointer; None when absent or
    unreadable (an unreadable pointer must not kill auto-resume — the
    candidate scan still finds every tag on disk)."""
    latest_path = os.path.join(load_dir, "latest")
    if not os.path.exists(latest_path):
        return None
    try:
        return fs.read_bytes_with_retry(latest_path).decode().strip() or None
    except (OSError, UnicodeDecodeError) as e:  # unreadable OR bit-rotted binary
        logger.warning(f"auto-resume: 'latest' pointer unreadable "
                       f"({type(e).__name__}: {e}); scanning candidate tags")
        return None


def _try_load_candidate(load_dir: str, tag: str, ckpt_engine):
    """One verified load attempt of ``tag``. Returns ``(loaded, cs,
    reason)``: the loaded dict + parsed client_state (or None when absent)
    with reason 'ok' (checksum-verified) or the no-manifest marker
    (readable legacy checkpoint), else ``(None, None, why)``. The sidecar
    client_state.json, when present, must parse — a torn sidecar from a
    pre-atomic-writer crash invalidates the candidate."""
    npz = os.path.join(load_dir, tag, "state.npz")
    if not (os.path.exists(npz) or os.path.exists(npz + ".orbax")):
        return None, None, "missing state.npz"
    try:
        loaded = ckpt_engine.load(npz)  # native engines checksum-verify here
    except Exception as e:
        return None, None, f"unloadable ({type(e).__name__}: {e})"
    cs = None
    cs_path = os.path.join(load_dir, tag, "client_state.json")
    if os.path.exists(cs_path):
        try:
            cs = json.loads(fs.read_bytes_with_retry(cs_path).decode())
        except Exception as e:
            return None, None, f"corrupt client_state.json ({type(e).__name__}: {e})"
    if os.path.exists(npz) and MANIFEST_KEY not in loaded.get("__meta__", {}):
        return loaded, cs, _NO_MANIFEST
    return loaded, cs, "ok"


def _auto_resume_load(load_dir: str, ckpt_engine):
    """Load the newest *valid* checkpoint under ``load_dir``: the 'latest'
    pointer is tried first, then every other candidate tag newest-first —
    each candidate (state + sidecar) is read at most once. Returns
    ``(tag, loaded, client_state)``; ``(None, None, None)`` when the
    directory holds no candidates at all. Manifest-verified candidates win;
    if none exists, the newest *readable* pre-manifest checkpoint (written
    before integrity manifests existed) is accepted with a warning so
    upgrading never strands an existing run. Raises
    :class:`CheckpointCorruptionError` when candidates exist but none is
    loadable (silently restarting from scratch after data loss is worse
    than failing loudly)."""
    latest_tag = _read_latest_tag(load_dir)
    candidates = list_checkpoint_tags(load_dir)
    ordered = ([latest_tag] if latest_tag else []) + \
        [t for t in candidates if t != latest_tag]
    if not ordered:
        return None, None, None
    skipped = []
    legacy = None  # newest readable pre-manifest candidate, held as last resort
    for t in ordered:
        loaded, cs, reason = _try_load_candidate(load_dir, t, ckpt_engine)
        if loaded is not None and reason == "ok":
            if skipped or t != latest_tag:
                record_event("checkpoint/corruption_fallbacks",
                             fallback_tag=t, latest=latest_tag,
                             skipped=[f"{s}: {r}" for s, r in skipped])
                logger.warning(
                    f"auto-resume: falling back to checkpoint '{t}' "
                    f"(latest='{latest_tag}'); skipped: "
                    + "; ".join(f"{s}: {r}" for s, r in skipped))
            return t, loaded, cs
        if loaded is not None and legacy is None:
            legacy = (t, loaded, cs)
        skipped.append((t, reason))
        logger.warning(f"auto-resume: skipping checkpoint '{t}': {reason}")
    if legacy is not None:
        t, loaded, cs = legacy
        if skipped and skipped != [(t, _NO_MANIFEST)]:
            record_event("checkpoint/corruption_fallbacks",
                         fallback_tag=t, latest=latest_tag, legacy=True)
        logger.warning(
            f"auto-resume: no manifest-verified checkpoint under {load_dir}; "
            f"resuming from pre-manifest checkpoint '{t}' "
            f"(unverified — re-save to gain integrity checking)")
        return t, loaded, cs
    record_event("checkpoint/load_failures", latest=latest_tag,
                 rejected=[f"{t}: {r}" for t, r in skipped])
    raise CheckpointCorruptionError(
        f"no valid checkpoint under {load_dir} "
        f"(latest='{latest_tag}'); candidates rejected: "
        + "; ".join(f"{t}: {r}" for t, r in skipped))


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                           load_optimizer_states: bool = True,
                           load_lr_scheduler_states: bool = True,
                           load_module_only: bool = False,
                           checkpoint_engine=None):
    ckpt_engine = checkpoint_engine or NativeCheckpointEngine()
    if tag is None:
        # Per-host resolution from each host's own filesystem view: every
        # host must reach the agreement collective below no matter its
        # local outcome (early return or raise here would strand peers in
        # the collective), and with divergent views the tag check either
        # raises everywhere (tag_validation=Fail) or logs loudly — hosts
        # silently resuming different steps is the one unacceptable result.
        err = None
        try:
            tag, loaded, cs = _auto_resume_load(load_dir, ckpt_engine)
        except CheckpointCorruptionError as e:
            tag, loaded, cs, err = None, None, None, e
        _validate_tag(engine, tag if tag is not None else
                      ("<corrupt>" if err is not None else "<none>"))
        if err is not None:
            raise err
        if tag is None:
            logger.warning(f"no checkpoint found under {load_dir}; nothing loaded")
            return None, {}
    else:
        base = os.path.join(load_dir, tag, "state.npz")
        if not (os.path.exists(base) or os.path.exists(base + ".orbax")):
            latest = _read_latest_tag(load_dir) or "<absent>"
            avail = list_checkpoint_tags(load_dir)
            raise FileNotFoundError(
                f"checkpoint tag '{tag}' not found under {load_dir}: no "
                f"{base}; 'latest' points to '{latest}'; available tags: "
                f"{avail if avail else 'none'}")
        loaded = ckpt_engine.load(base)
        cs = _read_client_state(load_dir, tag)

    # universal-by-default: re-shard global arrays onto the *current* plan
    from deepspeed_tpu.runtime.engine import TrainState

    params, missing_p = _unflatten_into(engine.state.params, loaded.get("params", {}))
    params = jax.device_put(params, engine.master_shardings)
    host_opt = getattr(engine, "_host_opt", None)
    if load_optimizer_states and not load_module_only and host_opt is not None \
            and "host_opt" in loaded:
        template = host_opt.state_template()
        hstate, _ = _unflatten_into(template, loaded["host_opt"], strict=False)
        host_opt.load_state_dict({
            "step": int(loaded.get("__meta__", {}).get("host_opt_step", 0)),
            "state": hstate})
        opt_state = engine.state.opt_state
    elif host_opt is not None:
        # host masters NOT restored (module-only load, or checkpoint saved
        # without offload): re-seed them from the just-loaded params, else the
        # next step rebuilds device params from stale random-init masters
        host_opt.init(params)
        opt_state = engine.state.opt_state
    elif load_optimizer_states and not load_module_only and "opt_state" in loaded \
            and engine.opt_shardings is not None and engine.opt_shardings != {}:
        opt_state, _ = _unflatten_into(engine.state.opt_state, loaded["opt_state"],
                                       strict=False)
        opt_state = jax.device_put(opt_state, engine.opt_shardings)
    else:
        opt_state = engine.state.opt_state
    if "scaler" in loaded and not load_module_only:
        scaler, _ = _unflatten_into(engine.state.scaler, loaded["scaler"], strict=False)
        scaler = jax.device_put(scaler, jax.tree_util.tree_map(
            lambda _: engine._replicated, engine.state.scaler))
    else:
        scaler = engine.state.scaler

    meta = loaded.get("__meta__", {})
    gstep = int(meta.get("global_step", 0))
    engine.state = TrainState(params=params, opt_state=opt_state, scaler=scaler,
                              global_step=jax.device_put(
                                  np.int32(gstep), engine._replicated))
    # keep host-side counters in sync even if client_state.json is missing,
    # so LR schedule / dropout folding resume from the right step
    engine.global_steps = gstep
    # restore the data-pipeline position (ISSUE 10): the loader resumes at
    # the exact batch after the checkpointed step; the engine's live
    # iterator (if any) is invalidated so the next pull honors it. Only
    # when the saved state describes THIS pipeline (identity fields
    # match) — warm-starting a checkpoint's weights onto a different
    # dataset must start that dataset from the top, not mid-stream.
    dataloader = getattr(engine, "training_dataloader", None)
    dl_state = meta.get("dataloader")
    if dataloader is not None and dl_state and \
            hasattr(dataloader, "load_state_dict"):
        matches = getattr(dataloader, "resume_state_matches",
                          lambda s: True)(dl_state)
        resumable = getattr(dataloader, "supports_deterministic_resume",
                            lambda: True)()
        if matches and resumable:
            dataloader.load_state_dict(dl_state)
            engine._train_iter = None
        else:
            logger.warning(
                "checkpoint dataloader state not restored (%s); the data "
                "pipeline starts from its current position",
                "external data_sampler" if not resumable
                else "identity mismatch — different dataset/batching")

    client_state = {}
    if cs is not None:
        engine.global_steps = cs.get("global_steps", gstep)
        engine.micro_steps = cs.get("micro_steps", 0)
        engine.skipped_steps = cs.get("skipped_steps", 0)
        if load_lr_scheduler_states and engine.lr_scheduler and cs.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(cs["lr_scheduler"])
        client_state = cs.get("client_state", {})
    record_event("checkpoint/loads", tag=tag, global_step=gstep)
    log_dist(f"loaded checkpoint {tag} from {load_dir} (reshard onto "
             f"{dict(zip(engine.topology.get_axis_names(), engine.topology.mesh_shape))})",
             ranks=[0])
    return os.path.join(load_dir, tag), client_state


def load_params_for_inference(load_dir: str, template, tag: Optional[str] = None):
    """Load ONLY the model params from an engine checkpoint, re-keyed onto
    ``template``'s pytree structure (reference InferenceEngine checkpoint-dict
    loading, inference/engine.py:338 load_model_with_checkpoint)."""
    ckpt_engine = NativeCheckpointEngine()
    if tag is None:
        tag, loaded, _ = _auto_resume_load(load_dir, ckpt_engine)
        if tag is None:
            raise FileNotFoundError(f"no checkpoint found under {load_dir}")
    else:
        loaded = ckpt_engine.load(os.path.join(load_dir, tag, "state.npz"))
    params, _ = _unflatten_into(template, loaded.get("params", {}))
    return params


def save_16bit_model(engine, save_dir: str, save_filename: str = "model_weights.npz"):
    """Consolidated 16-bit weights for serving (reference save_16bit_model:3213
    + zero_to_fp32 analog: with global arrays, consolidation is device_get)."""
    import ml_dtypes

    os.makedirs(save_dir, exist_ok=True)
    flat = _flatten_state(engine.state.params)
    # npz round-trips bf16 as raw void — store as uint16 views, tagged "@bf16"
    out = {}
    for k, v in flat.items():
        if v.dtype.kind == "f":
            out[k + "@bf16"] = v.astype(ml_dtypes.bfloat16).view(np.uint16)
        else:
            out[k] = v
    if not save_filename.endswith(".npz"):
        save_filename += ".npz"  # np.savez appends it anyway; keep path truthful
    if jax.process_index() == 0:
        np.savez(os.path.join(save_dir, save_filename), **out)
    return os.path.join(save_dir, save_filename)


def load_16bit_model(path: str) -> Dict[str, np.ndarray]:
    import ml_dtypes

    data = np.load(path)
    out = {}
    for k in data.files:
        if k.endswith("@bf16"):
            out[k[:-5]] = data[k].view(ml_dtypes.bfloat16)
        else:
            out[k] = data[k]
    return out


def zero_to_fp32(checkpoint_dir: str, output_file: str, tag: Optional[str] = None):
    """Offline reconstruction of full fp32 weights (reference
    utils/zero_to_fp32.py). Native checkpoints already store global fp32
    arrays, so this is a re-keying pass, runnable without any mesh."""
    if tag is None:
        with open(os.path.join(checkpoint_dir, "latest")) as f:
            tag = f.read().strip()
    loaded = NativeCheckpointEngine().load(os.path.join(checkpoint_dir, tag, "state.npz"))
    params = loaded.get("params", {})
    np.savez(output_file, **{k: v.astype(np.float32) if v.dtype.kind == "f" else v
                             for k, v in params.items()})
    return output_file
