"""Pipeline model description — analog of reference ``runtime/pipe/module.py``
(PipelineModule:85, LayerSpec:29, TiedLayerSpec:76).

A PipelineModule is a list of layer specs partitioned into stages. Each spec
builds a functional layer: ``init(rng) -> params`` and
``apply(params, x, *, rngs, train) -> x``. The PipelineEngine (pipe/engine.py)
executes stages over the 'pipe' mesh axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np


class LayerSpec:
    """Deferred layer construction (reference LayerSpec builds the nn.Module
    lazily on its stage's device; here laziness avoids materialising params
    for stages this process doesn't own)."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layers sharing parameters across stages (reference TiedLayerSpec:76) —
    e.g. tied input/output embeddings in GPT. ``key`` names the tie group;
    ``forward_fn`` optionally reinterprets the shared params."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, tied_weight_attr: str = "weight",
                 **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    """Partitioned layer-list model (reference PipelineModule:85).

    partition_method: 'uniform' | 'parameters' — same options as the
    reference (regex profiling TBD); parameters partitioning balances
    estimated param counts per stage.
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None, partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0, seed_layers: bool = False):
        self.layer_specs = list(layers)
        self.num_stages = num_stages or 1
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self._layers = [spec.build() if isinstance(spec, LayerSpec) else spec
                        for spec in self.layer_specs]
        self.parts = self._partition_layers()

    # ---------------------------------------------------------------- builder
    def _estimate_params(self, layer) -> int:
        try:
            shapes = jax.eval_shape(layer.init, jax.random.PRNGKey(0))
            return sum(int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes))
        except Exception:
            return 1

    def _partition_layers(self) -> List[int]:
        """Stage boundaries: parts[i] is the first layer of stage i
        (reference module.py _partition_layers)."""
        n, s = len(self._layers), self.num_stages
        assert n >= s, f"cannot split {n} layers into {s} stages"
        if self.partition_method == "uniform":
            bounds = [round(i * n / s) for i in range(s + 1)]
        else:  # 'parameters': balance cumulative param counts
            weights = np.array([self._estimate_params(l) for l in self._layers], dtype=np.float64)
            cum = np.cumsum(weights)
            total = cum[-1]
            bounds = [0]
            for i in range(1, s):
                bounds.append(int(np.searchsorted(cum, total * i / s)) + 1)
            bounds.append(n)
            # enforce monotonicity / at least one layer per stage
            for i in range(1, s + 1):
                bounds[i] = max(bounds[i], bounds[i - 1] + 1) if i < s + 1 else bounds[i]
            bounds[s] = n
        return bounds

    def stage_layers(self, stage_id: int):
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        return self._layers[lo:hi]

    @property
    def layers(self):
        return self._layers

    def tied_groups(self) -> Dict[str, List[int]]:
        groups: Dict[str, List[int]] = {}
        for i, spec in enumerate(self.layer_specs):
            if isinstance(spec, TiedLayerSpec):
                groups.setdefault(spec.key, []).append(i)
        return groups

    # ------------------------------------------------- whole-model functional
    def init(self, rng):
        params = []
        tied: Dict[str, Any] = {}
        for i, (spec, layer) in enumerate(zip(self.layer_specs, self._layers)):
            rng, sub = jax.random.split(rng)
            if isinstance(spec, TiedLayerSpec) and spec.key in tied:
                params.append(tied[spec.key])  # share the same pytree
            else:
                p = layer.init(sub) if hasattr(layer, "init") else {}
                params.append(p)
                if isinstance(spec, TiedLayerSpec):
                    tied[spec.key] = p
        return params

    def apply(self, params, batch, *, rngs=None, train: bool = False):
        x = batch["inputs"] if isinstance(batch, dict) else batch[0]
        labels = batch.get("labels") if isinstance(batch, dict) else batch[1]
        for i, layer in enumerate(self._layers):
            if hasattr(layer, "apply"):
                x = layer.apply(params[i], x, rngs=rngs, train=train)
            else:
                x = layer(x)
        if self.loss_fn is not None:
            loss = self.loss_fn(x, labels)
            return loss, {"loss": loss}
        return x, {}

    def logical_axes(self):
        return None
