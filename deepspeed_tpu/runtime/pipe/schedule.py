"""Pipeline schedules — declarative instruction streams.

Parity with reference ``runtime/pipe/schedule.py`` (PipeSchedule:49,
InferenceSchedule:135, TrainSchedule:189, DataParallelSchedule:252,
instruction classes :327-487). The reference's PipelineEngine interprets
these per-rank instruction streams imperatively with NCCL p2p; here the
SPMD executor (parallel/pipeline.py) compiles the *whole* schedule into one
XLA program, so these classes serve two roles:

  1. documentation/validation of the tick-level semantics (tested directly —
     the SPMD executor's microbatch/stage occupancy must agree with
     ``TrainSchedule``), and
  2. SPEC for a future host-driven inter-stage mode. No production code
     interprets these streams today — that becomes necessary only for
     multi-slice DCN pipelining, where stage boundaries cross slices and a
     single SPMD program cannot span the job. Deliberate deferral, recorded
     in COMPONENTS.md "Known gaps".
"""

from __future__ import annotations

from typing import Iterable, List


class PipeInstruction:
    """Base instruction (reference schedule.py:327)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return self.name == getattr(other, "name", None) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    """Instructions operating on a pipeline buffer slot (reference :395)."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule:
    """Yields lists of instructions per step for one stage
    (reference PipeSchedule:49)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterable[List[PipeInstruction]]:
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id: int) -> bool:
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id: int) -> bool:
        return 0 <= stage_id < self.stages

    def _buffer_idx(self, micro_batch_id: int) -> int:
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only stream (reference InferenceSchedule:135)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf))
                else:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B steady-state schedule (reference TrainSchedule:189).

    Tick layout: 2*(M+S-1) ticks; even ticks run forward work, odd ticks run
    backward work, arranged so each stage alternates 1-forward/1-backward in
    steady state and activation memory is bounded by ``num_pipe_buffers``.
    """

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            cmds: List[PipeInstruction] = []

            # exchange activations/grads with neighbours
            if self._valid_micro_batch(prev_micro_batch_id):
                prev_buf = self._buffer_idx(prev_micro_batch_id)
                if is_forward:
                    if not self.is_first_stage:
                        cmds.append(SendGrad(prev_buf))
                else:
                    if not self.is_last_stage:
                        cmds.append(SendActivation(prev_buf))
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if is_forward:
                    if self.is_first_stage:
                        cmds.append(LoadMicroBatch(buf))
                    else:
                        cmds.append(RecvActivation(buf))
                else:
                    if not self.is_last_stage:
                        cmds.append(RecvGrad(buf))

            # compute
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                cmds.append(ForwardPass(buf) if is_forward else BackwardPass(buf))

            # tail: grad reduction + optimizer step after the last backward
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def num_pipe_buffers(self):
        """Stages near the front need more in-flight buffers (reference :248)."""
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id: int):
        """(micro_batch_id, is_forward) for this tick (reference :258-300)."""
        if _is_even(step_id) and _is_even(self.stage_id):
            return self._even_step_forward_id(step_id), True
        if _is_odd(step_id) and _is_odd(self.stage_id):
            return self._odd_step_forward_id(step_id), True
        if _is_even(step_id) and _is_odd(self.stage_id):
            return self._even_step_backward_id(step_id), False
        if _is_odd(step_id) and _is_even(self.stage_id):
            return self._odd_step_backward_id(step_id), False
        raise RuntimeError("unreachable")

    def _even_step_forward_id(self, step_id):
        return step_id // 2 - self.stage_id // 2

    def _odd_step_forward_id(self, step_id):
        return (step_id - 1) // 2 - self.stage_id // 2

    def _even_step_backward_id(self, step_id):
        return step_id // 2 - self.stages + (self.stage_id + 1) // 2

    def _odd_step_backward_id(self, step_id):
        return (step_id - 1) // 2 - self.stages + (self.stage_id + 1) // 2 + 1


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference DataParallelSchedule:252)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds: List[PipeInstruction] = [LoadMicroBatch(0), ForwardPass(0),
                                           BackwardPass(0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


def _is_even(x: int) -> bool:
    return x % 2 == 0


def _is_odd(x: int) -> bool:
    return x % 2 != 0
