"""Pipeline-parallel training engine.

Parity target: reference ``runtime/pipe/engine.py`` (PipelineEngine:40,
train_batch:285, eval_batch:362, _exec_schedule:1287) — 1301 LoC of
instruction interpretation, p2p meta handshakes and buffer management.

TPU-native redesign: the whole 1F1B tick loop compiles into ONE XLA program
(parallel/pipeline.spmd_pipeline) — stage weights sharded over the 'pipe'
mesh axis, activations exchanged by ``ppermute`` over ICI, backward
pipelining by autodiff through the scanned schedule. The instruction
streams in ``schedule.py`` document/validate the tick semantics; this
engine never interprets them at runtime (no per-tick Python dispatch, no
meta handshake — shapes are static under jit).

Semantics parity notes:
  * micro_batches == gradient_accumulation_steps (reference engine.py:81).
  * forward()/backward()/step() are disabled exactly like the reference
    (:1175-1185) — ``train_batch``/``eval_batch`` are the only entries.
  * tied layers (TiedLayerSpec) hold ONE canonical param copy; both use
    sites read it, so autodiff *sums* their grads — the functional
    equivalent of the reference's ReduceTiedGrads allreduce over the tie
    group (:223).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.parallel.pipeline import spmd_pipeline
from deepspeed_tpu.parallel.topology import PIPE_AXIS
from deepspeed_tpu.runtime.engine import DeepSpeedEngine, TrainState
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec
from deepspeed_tpu.utils.logging import log_dist


class PipelineError(Exception):
    """Errors related to the use of deepspeed.PipelineModule (reference name)."""


def _layer_signature(layer) -> Tuple:
    """Stackability signature: same class + same param structure/shapes."""
    if not hasattr(layer, "init"):
        return (type(layer), None)
    shapes = jax.eval_shape(layer.init, jax.random.PRNGKey(0))
    leaves, treedef = jax.tree_util.tree_flatten(shapes)
    return (type(layer), str(treedef), tuple((tuple(l.shape), str(l.dtype)) for l in leaves))


class PipelinedModelAdapter:
    """Restructures a PipelineModule into (prefix, body, suffix) segments.

    body — the longest run of structurally identical, untied layers, trimmed
    to a multiple of num_stages; its params stack to leading dims
    ``[num_stages, layers_per_stage, ...]`` and execute via spmd_pipeline.
    prefix/suffix — everything before/after (embeddings, final norm, lm head);
    computed on all pipe ranks (replicated over 'pipe'), scanned over the
    microbatch stream.
    """

    def __init__(self, module: PipelineModule, num_stages: int, mesh, remat: bool = False):
        self.module = module
        self.num_stages = num_stages
        self.mesh = mesh
        self.remat = remat
        self._plan_segments()

    # ------------------------------------------------------------- segmenting
    def _plan_segments(self):
        specs = self.module.layer_specs
        layers = self.module.layers
        S = self.num_stages
        sigs = []
        for spec, layer in zip(specs, layers):
            tied = isinstance(spec, TiedLayerSpec)
            sigs.append(("tied",) if tied else _layer_signature(layer))

        # longest homogeneous run of stackable (non-tied, param-bearing) layers
        best = (0, 0)  # (start, length)
        i = 0
        n = len(layers)
        while i < n:
            j = i
            while (j < n and sigs[j] == sigs[i] and sigs[i][0] != "tied"
                   and sigs[i][1] is not None):
                j += 1
            if j - i > best[1]:
                best = (i, j - i)
            i = max(j, i + 1)
        start, length = best
        K = length // S  # layers per stage
        if K == 0:
            raise PipelineError(
                f"cannot pipeline: longest homogeneous layer run ({length}) is "
                f"shorter than num_stages ({S})")
        extra = length - K * S
        # extras join the prefix so the run stays contiguous
        self.body_start = start + extra
        self.body_end = start + length
        self.layers_per_stage = K
        self.prefix_idx = list(range(0, self.body_start))
        self.suffix_idx = list(range(self.body_end, n))
        self.body_layer = layers[self.body_start]

        # tied groups: key -> owner layer index (first occurrence)
        self.tie_owner: Dict[str, int] = {}
        self.tied_of: Dict[int, str] = {}
        for i, spec in enumerate(specs):
            if isinstance(spec, TiedLayerSpec):
                self.tied_of[i] = spec.key
                self.tie_owner.setdefault(spec.key, i)

    # ------------------------------------------------------------------- init
    def init(self, rng):
        layers = self.module.layers
        pre: Dict[str, Any] = {}
        post: Dict[str, Any] = {}
        tied: Dict[str, Any] = {}
        body_per_layer: List[Any] = []
        for i, layer in enumerate(layers):
            rng, sub = jax.random.split(rng)
            if i in self.tied_of:
                key = self.tied_of[i]
                if self.tie_owner[key] == i:
                    tied[key] = layer.init(sub)
                continue
            if not hasattr(layer, "init"):
                continue
            p = layer.init(sub)
            if self.body_start <= i < self.body_end:
                body_per_layer.append(p)
            elif i < self.body_start:
                pre[str(i)] = p
            else:
                post[str(i)] = p
        S, K = self.num_stages, self.layers_per_stage
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *body_per_layer)
        body = jax.tree_util.tree_map(
            lambda x: x.reshape((S, K) + x.shape[1:]), stacked)
        return {"pre": pre, "body": body, "post": post, "tied": tied}

    def logical_axes(self):
        """TP/pipe logical names per param. Body leaves get
        ('pipe_stage', 'layer') + the block layer's own per-param axes, so
        tensor parallelism composes with the pipe sharding (closes the
        pipe>1 × tp>1 composition gap; ref runtime/pipe/topology.py:244
        PipeModelDataParallelTopology)."""
        shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        layers = self.module.layers

        def layer_axes(i, leaf_tree):
            layer = layers[i]
            if hasattr(layer, "logical_axes"):
                return layer.logical_axes()
            return jax.tree_util.tree_map(lambda l: (None,) * l.ndim, leaf_tree)

        if hasattr(self.body_layer, "logical_axes"):
            blk = self.body_layer.logical_axes()
            _is_axes = lambda x: isinstance(x, tuple) and all(  # noqa: E731
                isinstance(e, (str, type(None))) for e in x)
            body = jax.tree_util.tree_map(
                lambda ax: ("pipe_stage", "layer") + tuple(ax), blk,
                is_leaf=_is_axes)
        else:
            body = jax.tree_util.tree_map(
                lambda l: ("pipe_stage",) + (None,) * (l.ndim - 1), shapes["body"])

        tied_axes = {}
        for key, owner in self.tie_owner.items():
            tied_axes[key] = layer_axes(owner, shapes["tied"][key])
        return {
            "pre": {k: layer_axes(int(k), v) for k, v in shapes["pre"].items()},
            "body": body,
            "post": {k: layer_axes(int(k), v) for k, v in shapes["post"].items()},
            "tied": tied_axes,
        }

    # ------------------------------------------------------------------ apply
    def _layer_params(self, params, i: int):
        if i in self.tied_of:
            return params["tied"][self.tied_of[i]]
        if i < self.body_start:
            return params["pre"].get(str(i))
        return params["post"].get(str(i))

    @staticmethod
    def layer_key(base, mb_id, layer_idx):
        """Per-(microbatch, global-layer) dropout key. Both executors (SPMD
        scan and host 1F1B interpreter) derive keys through this one
        function, so pipelined dropout is numerics-identical across them —
        the functional analog of the reference's CudaRNGStatesTracker
        threading (activation_checkpointing/checkpointing.py:121)."""
        return jax.random.fold_in(jax.random.fold_in(base, mb_id), layer_idx)

    def _run_segment(self, params, idx_list, x, train: bool,
                     rng_base=None, mb_id=None):
        for i in idx_list:
            layer = self.module.layers[i]
            spec = self.module.layer_specs[i]
            if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
                # tied re-use site reinterpreting the owner's params (e.g. the
                # lm head projecting through the embedding table)
                x = spec.forward_fn(self._layer_params(params, i), x)
            elif hasattr(layer, "apply"):
                k = (self.layer_key(rng_base, mb_id, i)
                     if rng_base is not None else None)
                x = layer.apply(self._layer_params(params, i), x, rngs=k, train=train)
            else:
                x = layer(x)
        return x

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, dict):
            inputs = batch.get("inputs", batch.get("input_ids"))
            labels = batch.get("labels", batch.get("y"))
        else:
            inputs, labels = batch[0], batch[1]
        return inputs, labels

    def apply(self, params, batch, *, rngs=None, train: bool = False):
        """batch leaves carry a leading [M] microbatch dim (the pipeline
        stream == gradient-accumulation microbatches, reference engine.py:81).
        ``rngs`` (a key, or {'dropout': key}) threads per-(microbatch, layer)
        dropout keys through prefix/body/suffix via ``layer_key``."""
        M = jax.tree_util.tree_leaves(batch)[0].shape[0]
        base = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        if not train:
            base = None
        K = self.layers_per_stage

        def pre_fn(args):
            mb, mb_id = args
            inputs, _ = self._split_batch(mb)
            return self._run_segment(params, self.prefix_idx, inputs, train,
                                     base, mb_id)

        xs = jax.lax.map(pre_fn, (batch, jnp.arange(M)))

        if base is None:
            def stage_fn(stage_params, x):
                def body(h, lp):
                    return self.body_layer.apply(
                        lp, h, rngs=None, train=train), None

                return jax.lax.scan(body, x, stage_params)[0]
        else:
            def stage_fn(stage_params, x, stage, mb_id):
                def body(h, lp_k):
                    lp, k = lp_k
                    key = self.layer_key(base, mb_id,
                                         self.body_start + stage * K + k)
                    return self.body_layer.apply(
                        lp, h, rngs=key, train=train), None

                return jax.lax.scan(body, x,
                                    (stage_params, jnp.arange(K)))[0]

        ys = spmd_pipeline(stage_fn, params["body"], xs, mesh=self.mesh,
                           num_stages=self.num_stages, num_microbatches=M,
                           remat=self.remat, index_args=base is not None)

        def post_fn(args):
            y, mb, mb_id = args
            _, labels = self._split_batch(mb)
            out = self._run_segment(params, self.suffix_idx, y, train,
                                    base, mb_id)
            if self.module.loss_fn is not None:
                return self.module.loss_fn(out, labels)
            return out

        losses = jax.lax.map(post_fn, (ys, batch, jnp.arange(M)))
        loss = jnp.mean(losses.astype(jnp.float32))
        return loss, {"loss": loss}


class PipelineEngine(DeepSpeedEngine):
    """Training engine for PipelineModule models (reference PipelineEngine:40)."""

    def __init__(self, module: PipelineModule, config, *, optimizer=None,
                 lr_scheduler=None, training_data=None, collate_fn=None,
                 topology=None, **kw):
        if not isinstance(module, PipelineModule):
            raise PipelineError("PipelineEngine requires a PipelineModule")
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        from deepspeed_tpu.utils import groups as groups_mod

        if not isinstance(config, DeepSpeedConfig):
            config = DeepSpeedConfig(config)
        if topology is None:
            topology = groups_mod.initialize(
                tp_size=config.tensor_parallel.tp_size,
                pp_size=max(config.pipeline.stages, module.num_stages),
                ep_size=config.expert_parallel.ep_size,
                sp_size=config.sequence_parallel.sp_size,
            )
        num_stages = topology.pipe_parallel_size
        self.pipeline_module = module
        adapter = PipelinedModelAdapter(
            module, num_stages, topology.mesh,
            remat=module.activation_checkpoint_interval > 0)
        super().__init__(adapter, config, optimizer=optimizer,
                         lr_scheduler=lr_scheduler, training_data=training_data,
                         collate_fn=collate_fn, topology=topology, **kw)
        self.num_stages = num_stages
        self.micro_batches = self.gas
        self._exec_mode = self.config.pipeline.executor
        if self._exec_mode not in ("spmd", "host_1f1b"):
            raise PipelineError(
                f"pipeline.executor must be 'spmd' or 'host_1f1b', "
                f"got {self._exec_mode!r}")
        self._executor_1f1b = None
        self._executor_1f1b_eval = {}  # M → executor (eval_batch sizes)
        self._1f1b_cast = None
        self._1f1b_apply = None
        self.last_1f1b_stats = None
        if self._exec_mode == "host_1f1b":
            from deepspeed_tpu.runtime.pipe.executor import (
                Schedule1F1BExecutor)

            self._executor_1f1b = Schedule1F1BExecutor(adapter, self.gas)
        log_dist(
            f"PipelineEngine: stages={num_stages} "
            f"executor={self._exec_mode} "
            f"body_layers=[{adapter.body_start},{adapter.body_end}) "
            f"layers/stage={adapter.layers_per_stage} "
            f"tied_groups={list(adapter.tie_owner)}", ranks=[0])

    # ------------------------------------------------- fused pipelined step
    def _build_train_step(self, batch=None):
        def train_step(state: TrainState, batch, lr, rng):
            scale = state.scaler.cur_scale

            def loss_fn(master_params):
                cparams = self._cast_for_compute(master_params)
                loss, metrics = self.module.apply(cparams, batch, rngs={"dropout": rng},
                                                  train=True)
                return loss * scale, metrics

            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
            grads = jax.tree_util.tree_map(
                lambda g, s: jax.lax.with_sharding_constraint(
                    g.astype(jnp.float32),
                    jax.sharding.NamedSharding(self.mesh, s)),
                grads, self.grad_specs)
            new_state, overflow, norm = self._apply_grads(state, grads, lr)
            out = {"loss": metrics["loss"], "overflow": overflow, "grad_norm": norm,
                   "loss_scale": state.scaler.cur_scale}
            return new_state, out

        self._compiled_train_step = jax.jit(train_step, donate_argnums=(0,))
        return self._compiled_train_step

    # --------------------------------------------- host-driven 1F1B executor
    def _run_fused_step(self, batch):
        if self._exec_mode == "host_1f1b":
            return self._run_host_1f1b_step(batch)
        return super()._run_fused_step(batch)

    def _run_host_1f1b_step(self, batch):
        """One train_batch via the instruction-stream interpreter
        (reference _exec_schedule:1287): per-stage jitted fwd/bwd driven by
        TrainSchedule, activation memory bounded by num_pipe_buffers; the
        epilogue (unscale/clip/optimizer/scale-update) reuses the engine's
        compiled _apply_grads."""
        import jax.numpy as jnp  # noqa: F811
        from deepspeed_tpu.runtime.engine import TRAIN_BATCH_TIMER

        import time

        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        t_start = time.perf_counter()
        batch = self._apply_curriculum(batch)
        batch = jax.device_put(batch, self._gas_batch_shardings(batch))
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        if self._1f1b_cast is None:
            self._1f1b_cast = jax.jit(self._cast_for_compute)

            def apply(state, grads, lr):
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads)
                # copy the used scale into an output: the input state is
                # donated, so its buffers must not be referenced afterwards
                used_scale = state.scaler.cur_scale * 1.0
                new_state, overflow, norm = self._apply_grads(state, grads, lr)
                return new_state, overflow, norm, used_scale

            # donate old state + grads: the epilogue must not double-buffer
            # params/opt state in the executor whose point is peak memory
            self._1f1b_apply = jax.jit(apply, donate_argnums=(0, 1))
        cparams = self._1f1b_cast(self.state.params)
        # keep the scale a device scalar — a host fetch here would fence
        # dispatch against the previous step's scaler update (tunnel RTT)
        scale = self.state.scaler.cur_scale
        # same per-step base key as the SPMD path (_build_train_step passes
        # rngs={'dropout': fold_in(dropout_rng, step)}) — the executor folds
        # (mb_id, layer) on top via layer_key, so both executors drop the
        # same units
        rng = jax.random.fold_in(self._dropout_rng, self.global_steps)
        loss, grads, stats = self._executor_1f1b.train_batch(
            cparams, batch, loss_scale=scale, rngs=rng)
        self.last_1f1b_stats = stats
        self.state, overflow, norm, scale = self._1f1b_apply(
            self.state, grads, lr)
        self._global_grad_norm = norm
        self.micro_steps += self.gas
        self.global_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        metrics = {"loss": loss, "overflow": overflow, "grad_norm": norm,
                   "loss_scale": scale}
        self._after_step(metrics)
        self.timers(TRAIN_BATCH_TIMER).stop(record=True)
        self.tput_timer.stop(global_step=True)
        if self.telemetry is not None:
            self._record_step_telemetry(
                metrics, batch, time.perf_counter() - t_start)
        if self._sync_each_step:
            # dstpu-lint: fence=opt-in per-step fence (config sync_each_step)
            jax.block_until_ready(self.state.params)
        return metrics["loss"]

    # --------------------------------------------------------------- user API
    def eval_batch(self, batch, compute_loss: bool = True):
        """reference eval_batch:362 — forward-only pipeline pass. In
        host_1f1b mode this interprets InferenceSchedule tick by tick (the
        path that still works when one XLA program cannot span the job)."""
        if self._exec_mode == "host_1f1b":
            leaves = jax.tree_util.tree_leaves(batch)
            if leaves and leaves[0].ndim >= 1 and not self._looks_stacked(batch):
                batch = jax.tree_util.tree_map(lambda x: x[None], batch)
            batch = jax.device_put(batch, self._gas_batch_shardings(batch))
            if self._1f1b_cast is None:
                self._1f1b_cast = jax.jit(self._cast_for_compute)
            M = jax.tree_util.tree_leaves(batch)[0].shape[0]
            ex = self._executor_1f1b
            if M != ex.M:
                # cache per-M executors: a fresh one per call would re-jit
                # its stage functions on every eval_batch
                if M not in self._executor_1f1b_eval:
                    from deepspeed_tpu.runtime.pipe.executor import (
                        Schedule1F1BExecutor)

                    self._executor_1f1b_eval[M] = Schedule1F1BExecutor(
                        self._executor_1f1b.adapter, M)
                ex = self._executor_1f1b_eval[M]
            return ex.eval_batch(self._1f1b_cast(self.state.params), batch)
        if self._compiled_eval is None:
            def ev(params, batch):
                cparams = self._cast_for_compute(params)
                loss, _ = self.module.apply(cparams, batch, rngs=None, train=False)
                return loss

            self._compiled_eval = jax.jit(ev)
        leaves = jax.tree_util.tree_leaves(batch)
        # accept both a single microbatch and a stacked [M, ...] stream
        if leaves and leaves[0].ndim >= 1 and not self._looks_stacked(batch):
            batch = jax.tree_util.tree_map(lambda x: x[None], batch)
        batch = jax.device_put(batch, self._gas_batch_shardings(batch))
        return self._compiled_eval(self.state.params, batch)

    def _looks_stacked(self, batch) -> bool:
        inputs, _ = PipelinedModelAdapter._split_batch(batch)
        return inputs.ndim >= 3

    # disabled entry points (reference engine.py:1175-1185)
    def forward(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    __call__ = forward

    def backward(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    def step(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    # ------------------------------------------------------------- stage info
    def is_first_stage(self) -> bool:
        return True  # single-controller SPMD: every process drives all stages

    def is_last_stage(self) -> bool:
        return True

    def is_pipe_parallel(self) -> bool:
        return self.num_stages > 1
