"""Host-driven 1F1B pipeline executor — interprets ``schedule.py`` streams.

Parity target: reference ``runtime/pipe/engine.py:1287 _exec_schedule`` — the
instruction interpreter that binds ``TrainSchedule``'s per-stage tick streams
(schedule.py:189) to compute/communication callbacks with a bounded buffer
pool (``num_pipe_buffers``, schedule.py:248).

Relationship to the SPMD engine (parallel/pipeline.py): the SPMD scan
compiles the whole schedule into one XLA program, but its backward is
autodiff's replay — GPipe-shaped, holding all M microbatch activations
(unless remat'd). This executor interprets the 1F1B stream tick by tick over
per-stage jitted functions, so at most ``num_pipe_buffers(stage) <= stages``
microbatch activations are ever live per stage — activation memory is
bounded by pipeline DEPTH, not microbatch count, exactly like the reference.
It is also the execution model that extends to multi-slice DCN pipelining,
where one SPMD program cannot span the job and stage boundaries become real
transfers.

Design notes (TPU-first):
  * BackwardPass rematerializes the stage forward inside ``jax.vjp`` — a
    buffer holds only the stage's INPUT activation (plus the pending output
    grad), the jax.checkpoint-style trade the reference makes with
    activation checkpointing. Peak live bytes per stage ~= num_pipe_buffers
    * activation_size.
  * Sends/recvs within a tick run in two phases (all sends first): the
    reference orders each rank's cmds the same way, relying on p2p blocking
    for cross-rank pairing; a FIFO per directed edge replaces the NCCL
    channel. In a multi-slice deployment these become real
    ``jax.device_put`` transfers — the interpreter is transfer-agnostic.
  * Per-stage fwd/bwd are jitted once and REUSED across middle stages
    (identical shapes), so compile count is O(1) in depth.
  * The tied-weight sum (ReduceTiedGrads, reference :223) falls out of
    accumulation: stage 0's prefix grads and the last stage's suffix grads
    both accumulate into the same ``tied`` slot.
"""

from __future__ import annotations

import functools
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.pipe import schedule as sched


def _tree_add(a, b):
    if a is None:
        return b
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_bytes(tree) -> int:
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "nbytes"))


class _Buffer:
    """One pipeline buffer slot (reference engine.py pipe_buffers)."""

    __slots__ = ("mb_id", "x", "y", "gy", "gx")

    def __init__(self):
        self.mb_id = None   # microbatch index (FIFO order)
        self.x = None       # stage input activation (kept until backward)
        self.y = None       # stage output (kept until sent)
        self.gy = None      # received output grad
        self.gx = None      # input grad (kept until sent)

    def live_bytes(self) -> int:
        return sum(_tree_bytes(v) for v in (self.x, self.y, self.gy, self.gx)
                   if v is not None)


class Schedule1F1BExecutor:
    """Interpret Train/Inference schedules over a PipelinedModelAdapter.

    ``train_batch(params, batch)`` returns ``(mean_loss, grads, stats)``
    where grads matches the params structure and stats records the measured
    peak buffer occupancy / live activation bytes per stage (the memory
    bound this executor exists to enforce).
    """

    def __init__(self, adapter, micro_batches: int,
                 schedule_cls=sched.TrainSchedule):
        self.adapter = adapter
        self.S = adapter.num_stages
        self.M = micro_batches
        self.schedule_cls = schedule_cls
        assert self.S >= 2, (
            "the 1F1B executor is for multi-stage pipelines; single-stage "
            "training uses the engine's fused step (DataParallelSchedule)")
        self._build_fns()

    # ------------------------------------------------------------ stage fns
    def _build_fns(self):
        # NOTE on dropout rngs: stage fns pass rngs=None to layers, the same
        # as PipelinedModelAdapter.apply on the SPMD path — pipeline layers
        # with stochastic behavior are not rng-threaded on EITHER executor
        # today (the two paths stay numerically identical).
        ad = self.adapter

        def stage_body(body_s, x, train):
            def body(h, lp):
                return ad.body_layer.apply(lp, h, rngs=None,
                                           train=train), None
            return jax.lax.scan(body, x, body_s)[0]

        def first_fwd(shared, body0, mb, *, train):
            inputs, _ = ad._split_batch(mb)
            h = ad._run_segment(shared, ad.prefix_idx, inputs, train)
            return stage_body(body0, h, train)

        def mid_fwd(body_s, x, *, train):
            return stage_body(body_s, x, train)

        def last_loss(body_last, shared, x, mb, *, train):
            _, labels = ad._split_batch(mb)
            y = stage_body(body_last, x, train)
            out = ad._run_segment(shared, ad.suffix_idx, y, train)
            if ad.module.loss_fn is not None:
                return ad.module.loss_fn(out, labels)
            return out

        # shared params (pre/post/tied) enter first/last stages so their
        # grads flow; vjp wrt (shared, body, x) as needed
        self._first_fwd = jax.jit(functools.partial(first_fwd, train=True))
        self._mid_fwd = jax.jit(functools.partial(mid_fwd, train=True))
        self._first_fwd_eval = jax.jit(functools.partial(first_fwd,
                                                         train=False))
        self._mid_fwd_eval = jax.jit(functools.partial(mid_fwd, train=False))
        self._last_fwd_eval = jax.jit(functools.partial(last_loss,
                                                        train=False))

        def first_bwd(shared, body0, mb, gy):
            _, vjp = jax.vjp(
                lambda s, b: first_fwd(s, b, mb, train=True), shared, body0)
            return vjp(gy)  # (g_shared, g_body0)

        def mid_bwd(body_s, x, gy):
            _, vjp = jax.vjp(
                lambda b, xx: mid_fwd(b, xx, train=True), body_s, x)
            return vjp(gy)  # (g_body, gx)

        def last_bwd(body_last, shared, x, mb, dloss):
            loss, vjp = jax.vjp(
                lambda b, s, xx: last_loss(b, s, xx, mb, train=True),
                body_last, shared, x)
            g_body, g_shared, gx = vjp(dloss)
            return loss, g_body, g_shared, gx

        self._first_bwd = jax.jit(first_bwd)
        self._mid_bwd = jax.jit(mid_bwd)
        self._last_bwd = jax.jit(last_bwd)

    @staticmethod
    def _shared_of(params):
        return {"pre": params["pre"], "post": params["post"],
                "tied": params["tied"]}

    # ------------------------------------------------------------ execution
    def train_batch(self, params, batch,
                    optimizer_step_fn: Optional[Callable] = None,
                    loss_scale=1.0):
        """``batch`` leaves carry a leading [M] microbatch dim. Interprets
        each stage's TrainSchedule stream tick-locked; returns
        (mean_loss, grads, stats). ``optimizer_step_fn(grads)`` runs at the
        OptimizerStep instruction when provided. ``loss_scale`` (python
        float or device scalar — device keeps dispatch async) multiplies
        the seed cotangent (fp16 dynamic-loss-scaling semantics — the
        engine's _apply_grads unscales); the reported loss is UNscaled."""
        S, M = self.S, self.M
        ad = self.adapter
        shared = self._shared_of(params)
        # slice each stage's body params ONCE per batch (the pipe-sharded
        # stack reshards on slicing; per-instruction slicing would repay
        # that transfer every tick)
        bodies = [jax.tree_util.tree_map(lambda a, s=s: a[s], params["body"])
                  for s in range(S)]
        body_of = lambda s: bodies[s]  # noqa: E731
        mb_of = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda x: x[i], batch)

        schedules = [self.schedule_cls(M, S, s) for s in range(S)]
        streams = [list(s.steps()) for s in schedules]
        n_ticks = max(len(st) for st in streams)
        bufs = [[_Buffer() for _ in range(schedules[s].num_pipe_buffers())]
                for s in range(S)]
        act_wire = [deque() for _ in range(S)]   # edge s-1 -> s
        grad_wire = [deque() for _ in range(S)]  # edge s+1 -> s
        load_count = [0] * S    # LoadMicroBatch FIFO per stage
        recv_count = [0] * S    # RecvActivation FIFO per stage (mb order)
        g_shared = None
        g_body: List[Any] = [None] * S
        losses = []
        dloss = jnp.asarray(loss_scale, jnp.float32) / M
        stats = {"peak_buffers": [0] * S, "peak_live_bytes": [0] * S,
                 "num_pipe_buffers": [schedules[s].num_pipe_buffers()
                                      for s in range(S)]}
        opt_ran = False

        for tick in range(n_ticks):
            cmds = [streams[s][tick] if tick < len(streams[s]) else []
                    for s in range(S)]
            # phase 1: sends (always reference completed earlier-tick data)
            for s in range(S):
                for c in cmds[s]:
                    buf = bufs[s][c.buffer_id] if isinstance(
                        c, sched.BufferOpInstruction) else None
                    if isinstance(c, sched.SendActivation):
                        act_wire[s + 1].append(buf.y)
                        buf.y = None
                    elif isinstance(c, sched.SendGrad):
                        grad_wire[s - 1].append(buf.gx)
                        buf.gx = None
            # phase 2: recv + compute
            for s in range(S):
                for c in cmds[s]:
                    buf = bufs[s][c.buffer_id] if isinstance(
                        c, sched.BufferOpInstruction) else None
                    if isinstance(c, sched.LoadMicroBatch):
                        buf.mb_id = load_count[s]
                        load_count[s] += 1
                    elif isinstance(c, sched.RecvActivation):
                        assert act_wire[s], (
                            f"tick {tick} stage {s}: RecvActivation with an "
                            "empty wire — schedule pairing violated")
                        buf.x = act_wire[s].popleft()
                        buf.mb_id = recv_count[s]
                        recv_count[s] += 1
                    elif isinstance(c, sched.RecvGrad):
                        assert grad_wire[s], (
                            f"tick {tick} stage {s}: RecvGrad with an empty "
                            "wire — schedule pairing violated")
                        buf.gy = grad_wire[s].popleft()
                    elif isinstance(c, sched.ForwardPass):
                        if s == 0:
                            buf.x = mb_of(buf.mb_id)
                            y = self._first_fwd(shared, body_of(0), buf.x)
                        elif s < S - 1:
                            y = self._mid_fwd(body_of(s), buf.x)
                        else:
                            # last stage: loss+backward fuse in BackwardPass
                            # (value_and_grad) — forward here would double
                            # the stage compute under remat-backward
                            continue
                        if s < S - 1:
                            buf.y = y
                    elif isinstance(c, sched.BackwardPass):
                        if s == S - 1:
                            loss, gb, gs, gx = self._last_bwd(
                                body_of(s), shared, buf.x,
                                mb_of(buf.mb_id), dloss)
                            losses.append(loss)
                            g_shared = _tree_add(g_shared, gs)
                            g_body[s] = _tree_add(g_body[s], gb)
                            buf.gx = gx
                        elif s > 0:
                            gb, gx = self._mid_bwd(body_of(s), buf.x, buf.gy)
                            g_body[s] = _tree_add(g_body[s], gb)
                            buf.gx = gx
                        else:
                            gs, gb = self._first_bwd(
                                shared, body_of(0), buf.x, buf.gy)
                            g_shared = _tree_add(g_shared, gs)
                            g_body[0] = _tree_add(g_body[0], gb)
                        buf.x = None   # memory release point (1F1B bound)
                        buf.gy = None
                    elif isinstance(c, sched.ReduceTiedGrads):
                        pass  # tied sum falls out of g_shared accumulation
                    elif isinstance(c, sched.ReduceGrads):
                        pass  # data-axis reduction: GSPMD inside stage fns
                    elif isinstance(c, sched.OptimizerStep):
                        opt_ran = True
            # memory accounting at tick boundary
            for s in range(S):
                live = [b for b in bufs[s] if b.live_bytes() > 0]
                stats["peak_buffers"][s] = max(stats["peak_buffers"][s],
                                               len(live))
                stats["peak_live_bytes"][s] = max(
                    stats["peak_live_bytes"][s],
                    sum(b.live_bytes() for b in live))

        assert len(losses) == M, f"expected {M} losses, got {len(losses)}"
        grads = {
            "pre": g_shared["pre"], "post": g_shared["post"],
            "tied": g_shared["tied"],
            "body": jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *g_body),
        }
        mean_loss = sum(jax.tree_util.tree_leaves(losses)) / M
        if opt_ran and optimizer_step_fn is not None:
            optimizer_step_fn(grads)
        return mean_loss, grads, stats

    def eval_batch(self, params, batch):
        """Forward-only interpretation of InferenceSchedule."""
        S, M = self.S, self.M
        shared = self._shared_of(params)
        bodies = [jax.tree_util.tree_map(lambda a, s=s: a[s], params["body"])
                  for s in range(S)]
        body_of = lambda s: bodies[s]  # noqa: E731
        mb_of = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda x: x[i], batch)

        schedules = [sched.InferenceSchedule(M, S, s) for s in range(S)]
        streams = [list(s.steps()) for s in schedules]
        n_ticks = max(len(st) for st in streams)
        bufs = [[_Buffer() for _ in range(schedules[s].num_pipe_buffers())]
                for s in range(S)]
        act_wire = [deque() for _ in range(S)]
        counters = [0] * S
        losses = []
        for tick in range(n_ticks):
            cmds = [streams[s][tick] if tick < len(streams[s]) else []
                    for s in range(S)]
            # forward-only: InferenceSchedule sends in the SAME tick as the
            # forward (unlike TrainSchedule's previous-tick sends), so one
            # ascending-stage pass in cmd order gives correct send/recv
            # pairing — the producer stage always runs before its consumer
            for s in range(S):
                for c in cmds[s]:
                    buf = bufs[s][c.buffer_id] if isinstance(
                        c, sched.BufferOpInstruction) else None
                    if isinstance(c, sched.LoadMicroBatch):
                        buf.mb_id = counters[s]
                        counters[s] += 1
                    elif isinstance(c, sched.RecvActivation):
                        assert act_wire[s], (
                            f"tick {tick} stage {s}: RecvActivation with an "
                            "empty wire — schedule pairing violated")
                        buf.x = act_wire[s].popleft()
                        buf.mb_id = counters[s]
                        counters[s] += 1
                    elif isinstance(c, sched.SendActivation):
                        act_wire[s + 1].append(buf.y)
                        buf.y = None
                    elif isinstance(c, sched.ForwardPass):
                        if s == 0 and S > 1:
                            buf.y = self._first_fwd_eval(
                                shared, body_of(0), mb_of(buf.mb_id))
                        elif s < S - 1:
                            buf.y = self._mid_fwd_eval(body_of(s), buf.x)
                        else:
                            losses.append(self._last_fwd_eval(
                                body_of(s), shared, buf.x, mb_of(buf.mb_id)))
                            buf.x = None
        assert len(losses) == M
        return sum(jax.tree_util.tree_leaves(losses)) / M
