"""Host-driven 1F1B pipeline executor — interprets ``schedule.py`` streams.

Parity target: reference ``runtime/pipe/engine.py:1287 _exec_schedule`` — the
instruction interpreter that binds ``TrainSchedule``'s per-stage tick streams
(schedule.py:189) to compute/communication callbacks with a bounded buffer
pool (``num_pipe_buffers``, schedule.py:248).

Relationship to the SPMD engine (parallel/pipeline.py): the SPMD scan
compiles the whole schedule into one XLA program, but its backward is
autodiff's replay — GPipe-shaped, holding all M microbatch activations
(unless remat'd). This executor interprets the 1F1B stream tick by tick over
per-stage jitted functions, so at most ``num_pipe_buffers(stage) <= stages``
microbatch activations are ever live per stage — activation memory is
bounded by pipeline DEPTH, not microbatch count, exactly like the reference.
It is also the execution model that extends to multi-slice DCN pipelining,
where one SPMD program cannot span the job and stage boundaries become real
transfers.

Design notes (TPU-first):
  * BackwardPass rematerializes the stage forward inside ``jax.vjp`` — a
    buffer holds only the stage's INPUT activation (plus the pending output
    grad), the jax.checkpoint-style trade the reference makes with
    activation checkpointing. Peak live bytes per stage ~= num_pipe_buffers
    * activation_size.
  * Sends/recvs within a tick run in two phases (all sends first): the
    reference orders each rank's cmds the same way, relying on p2p blocking
    for cross-rank pairing; a FIFO per directed edge replaces the NCCL
    channel. In a multi-slice deployment these become real
    ``jax.device_put`` transfers — the interpreter is transfer-agnostic.
  * Per-stage fwd/bwd are jitted once and REUSED across middle stages
    (identical shapes), so compile count is O(1) in depth.
  * The tied-weight sum (ReduceTiedGrads, reference :223) falls out of
    accumulation: stage 0's prefix grads and the last stage's suffix grads
    both accumulate into the same ``tied`` slot.
"""

from __future__ import annotations

import functools
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.pipe import schedule as sched


def _axes_in(entry, axis_names) -> bool:
    """True when a PartitionSpec entry (axis name or tuple of names) only
    references axes present in ``axis_names``."""
    if isinstance(entry, (tuple, list)):
        return all(e in axis_names for e in entry)
    return entry in axis_names


def _tree_add(a, b):
    if a is None:
        return b
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_bytes(tree) -> int:
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "nbytes"))


class _Buffer:
    """One pipeline buffer slot (reference engine.py pipe_buffers)."""

    __slots__ = ("mb_id", "x", "y", "gy", "gx")

    def __init__(self):
        self.mb_id = None   # microbatch index (FIFO order)
        self.x = None       # stage input activation (kept until backward)
        self.y = None       # stage output (kept until sent)
        self.gy = None      # received output grad
        self.gx = None      # input grad (kept until sent)

    def live_bytes(self) -> int:
        return sum(_tree_bytes(v) for v in (self.x, self.y, self.gy, self.gx)
                   if v is not None)


class Schedule1F1BExecutor:
    """Interpret Train/Inference schedules over a PipelinedModelAdapter.

    ``train_batch(params, batch)`` returns ``(mean_loss, grads, stats)``
    where grads matches the params structure and stats records the measured
    peak buffer occupancy / live activation bytes per stage (the memory
    bound this executor exists to enforce).
    """

    def __init__(self, adapter, micro_batches: int,
                 schedule_cls=sched.TrainSchedule):
        self.adapter = adapter
        self.S = adapter.num_stages
        self.M = micro_batches
        self.schedule_cls = schedule_cls
        assert self.S >= 2, (
            "the 1F1B executor is for multi-stage pipelines; single-stage "
            "training uses the engine's fused step (DataParallelSchedule)")
        self._fns_cache: Dict[bool, Dict[str, Callable]] = {}
        self.submeshes = self._build_submeshes()

    # ------------------------------------------------------- stage submeshes
    def _build_submeshes(self):
        """One submesh per stage: the full mesh's devices at pipe index s,
        keeping every other axis. Stage params/compute are PINNED to their
        submesh and every inter-stage wire is a real jax.device_put transfer
        — the placement model the reference's PP uses (module.py:85
        partitions layers onto disjoint rank sets; p2p.py:50 moves the
        boundary tensors), and the execution model that extends to
        multi-slice DCN pipelining where one SPMD program cannot span the
        job. Returns None (single-mesh fallback: stages replicated over
        'pipe') when the mesh lacks a pipe axis of size S."""
        from deepspeed_tpu.parallel.topology import PIPE_AXIS

        mesh = getattr(self.adapter, "mesh", None)
        if mesh is None or PIPE_AXIS not in mesh.axis_names:
            return None
        if mesh.shape[PIPE_AXIS] != self.S:
            return None
        ax = list(mesh.axis_names).index(PIPE_AXIS)
        names = tuple(n for n in mesh.axis_names if n != PIPE_AXIS)
        subs = []
        for s in range(self.S):
            devs = np.take(np.asarray(mesh.devices), s, axis=ax)
            subs.append(jax.sharding.Mesh(devs, names))
        return subs

    def stage_device_sets(self):
        """Per-stage device sets (disjoint when submeshes are active) —
        asserted by tests; the single-mesh fallback returns the full set
        for every stage."""
        if self.submeshes is None:
            mesh = getattr(self.adapter, "mesh", None)
            full = frozenset(np.asarray(mesh.devices).ravel().tolist()) \
                if mesh is not None else frozenset()
            return [full] * self.S
        return [frozenset(np.asarray(m.devices).ravel().tolist())
                for m in self.submeshes]

    @staticmethod
    def _spec_without_lead(arr):
        """PartitionSpec of ``arr`` minus its leading (pipe) entry — the
        intra-stage sharding a stage-sliced leaf keeps on its submesh."""
        spec = getattr(getattr(arr, "sharding", None), "spec", None)
        if spec is None:
            return P()
        return P(*tuple(spec)[1:])

    @staticmethod
    def _spec_of(arr):
        spec = getattr(getattr(arr, "sharding", None), "spec", None)
        return P() if spec is None else P(*tuple(spec))

    def _to_stage(self, tree, s, stacked_src=None):
        """Transfer a pytree to stage ``s``'s submesh, preserving each
        leaf's intra-stage sharding. This IS the pipeline wire: between
        submeshes it is a real device-to-device (ICI/DCN) transfer.
        ``stacked_src`` (the [S, ...] pipe-stacked source tree) supplies
        the target spec for freshly stage-sliced leaves: the source spec
        minus its leading 'pipe' entry."""
        if self.submeshes is None:
            return tree
        sub = self.submeshes[s]

        def put(x, src=None):
            spec = (self._spec_without_lead(src) if src is not None
                    else self._spec_of(x))
            # drop spec entries referring to axes absent from the submesh
            entries = tuple(e for e in tuple(spec)
                            if e is None or _axes_in(e, sub.axis_names))
            return jax.device_put(x, NamedSharding(sub, P(*entries)))

        if stacked_src is not None:
            return jax.tree_util.tree_map(put, tree, stacked_src)
        return jax.tree_util.tree_map(put, tree)

    def _from_stages(self, per_stage):
        """Stack per-stage grad pytrees (each living on its stage submesh)
        back onto the FULL mesh in the params['body'] layout: leaves move
        submesh -> full mesh (the reverse wire), then stack under the
        pipe-sharded spec so the engine epilogue sees the same layout the
        SPMD path produces."""
        mesh = self.adapter.mesh
        if self.submeshes is None:
            return jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *per_stage)

        from deepspeed_tpu.parallel.topology import PIPE_AXIS

        def stack(*xs):
            spec_rest = self._spec_of(xs[0])
            entries = tuple(e for e in tuple(spec_rest)
                            if e is None or _axes_in(e, mesh.axis_names))
            moved = [jax.device_put(
                x, NamedSharding(mesh, P(*entries))) for x in xs]
            return jax.device_put(
                jnp.stack(moved), NamedSharding(mesh, P(PIPE_AXIS, *entries)))

        return jax.tree_util.tree_map(stack, *per_stage)

    def _to_full(self, tree):
        """Reverse wire: move a stage-resident pytree onto the full mesh
        (replicated over 'pipe'), keeping intra-stage sharding."""
        if self.submeshes is None:
            return tree
        mesh = self.adapter.mesh

        def put(x):
            spec = self._spec_of(x)
            entries = tuple(e for e in tuple(spec)
                            if e is None or _axes_in(e, mesh.axis_names))
            return jax.device_put(x, NamedSharding(mesh, P(*entries)))

        return jax.tree_util.tree_map(put, tree)

    # ------------------------------------------------------------ stage fns
    def _fns(self, use_rng: bool) -> Dict[str, Callable]:
        """Jitted per-stage fwd/bwd functions. Two static variants: without
        rngs (layers see rngs=None — dropout off, the pre-round-4 program)
        and with rngs, where every layer's key is
        ``PipelinedModelAdapter.layer_key(base, mb_id, global_layer_idx)``
        — the SAME derivation the SPMD scan uses, so the two executors stay
        numerics-identical with dropout enabled. stage/mb_id are traced
        int32 scalars (mid-stage fns are reused across stages; a python int
        would recompile per stage/microbatch)."""
        if use_rng in self._fns_cache:
            return self._fns_cache[use_rng]
        ad = self.adapter
        K = ad.layers_per_stage
        key_of = type(ad).layer_key

        def stage_body(body_s, x, train, stage, mb_id, base):
            if base is None:
                def body(h, lp):
                    return ad.body_layer.apply(lp, h, rngs=None,
                                               train=train), None
                return jax.lax.scan(body, x, body_s)[0]

            def body(h, lp_k):
                lp, k = lp_k
                key = key_of(base, mb_id, ad.body_start + stage * K + k)
                return ad.body_layer.apply(lp, h, rngs=key,
                                           train=train), None
            return jax.lax.scan(body, x, (body_s, jnp.arange(K)))[0]

        def first_fwd(shared, body0, mb, mb_id=None, base=None, *, train):
            inputs, _ = ad._split_batch(mb)
            h = ad._run_segment(shared, ad.prefix_idx, inputs, train,
                                base, mb_id)
            return stage_body(body0, h, train, 0, mb_id, base)

        def mid_fwd(body_s, x, stage=None, mb_id=None, base=None, *, train):
            return stage_body(body_s, x, train, stage, mb_id, base)

        def last_loss(body_last, shared, x, mb, mb_id=None, base=None, *,
                      train):
            _, labels = ad._split_batch(mb)
            y = stage_body(body_last, x, train, self.S - 1, mb_id, base)
            out = ad._run_segment(shared, ad.suffix_idx, y, train,
                                  base, mb_id)
            if ad.module.loss_fn is not None:
                return ad.module.loss_fn(out, labels)
            return out

        def first_bwd(shared, body0, mb, gy, mb_id=None, base=None):
            _, vjp = jax.vjp(
                lambda s, b: first_fwd(s, b, mb, mb_id, base, train=True),
                shared, body0)
            return vjp(gy)  # (g_shared, g_body0)

        def mid_bwd(body_s, x, gy, stage=None, mb_id=None, base=None):
            _, vjp = jax.vjp(
                lambda b, xx: mid_fwd(b, xx, stage, mb_id, base, train=True),
                body_s, x)
            return vjp(gy)  # (g_body, gx)

        def last_bwd(body_last, shared, x, mb, dloss, mb_id=None, base=None):
            loss, vjp = jax.vjp(
                lambda b, s, xx: last_loss(b, s, xx, mb, mb_id, base,
                                           train=True),
                body_last, shared, x)
            g_body, g_shared, gx = vjp(dloss)
            return loss, g_body, g_shared, gx

        # shared params (pre/post/tied) enter first/last stages so their
        # grads flow; vjp wrt (shared, body, x) as needed. Without rngs the
        # optional args are dropped so compiled signatures match round 3.
        if use_rng:
            fns = {
                "first_fwd": jax.jit(functools.partial(first_fwd, train=True)),
                "mid_fwd": jax.jit(functools.partial(mid_fwd, train=True)),
                "first_bwd": jax.jit(first_bwd),
                "mid_bwd": jax.jit(mid_bwd),
                "last_bwd": jax.jit(last_bwd),
            }
        else:
            fns = {
                "first_fwd": jax.jit(lambda s, b, mb: first_fwd(
                    s, b, mb, train=True)),
                "mid_fwd": jax.jit(lambda b, x: mid_fwd(b, x, train=True)),
                "first_bwd": jax.jit(lambda s, b, mb, gy: first_bwd(
                    s, b, mb, gy)),
                "mid_bwd": jax.jit(lambda b, x, gy: mid_bwd(b, x, gy)),
                "last_bwd": jax.jit(lambda b, s, x, mb, d: last_bwd(
                    b, s, x, mb, d)),
            }
        # eval is always rng-free (dropout off)
        fns["first_fwd_eval"] = jax.jit(lambda s, b, mb: first_fwd(
            s, b, mb, train=False))
        fns["mid_fwd_eval"] = jax.jit(lambda b, x: mid_fwd(b, x, train=False))
        fns["last_fwd_eval"] = jax.jit(lambda b, s, x, mb: last_loss(
            b, s, x, mb, train=False))
        self._fns_cache[use_rng] = fns
        return fns

    @staticmethod
    def _shared_of(params):
        return {"pre": params["pre"], "post": params["post"],
                "tied": params["tied"]}

    # ------------------------------------------------------------ execution
    def train_batch(self, params, batch,
                    optimizer_step_fn: Optional[Callable] = None,
                    loss_scale=1.0, rngs=None):
        """``batch`` leaves carry a leading [M] microbatch dim. Interprets
        each stage's TrainSchedule stream tick-locked; returns
        (mean_loss, grads, stats). ``optimizer_step_fn(grads)`` runs at the
        OptimizerStep instruction when provided. ``loss_scale`` (python
        float or device scalar — device keeps dispatch async) multiplies
        the seed cotangent (fp16 dynamic-loss-scaling semantics — the
        engine's _apply_grads unscales); the reported loss is UNscaled.
        ``rngs`` (a key, or {'dropout': key}) enables per-(microbatch,
        layer) dropout keys — derivation shared with the SPMD path via
        ``PipelinedModelAdapter.layer_key``."""
        S, M = self.S, self.M
        ad = self.adapter
        base = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        fns = self._fns(base is not None)
        # traced scalars (a python int would recompile per value)
        _i32 = lambda v: jnp.asarray(v, jnp.int32)  # noqa: E731
        # stage placement: shared params pinned to the two end stages (the
        # only ones that touch them); body slices pinned per stage; the rng
        # base replicated onto every stage's submesh
        shared = self._shared_of(params)
        shared_first = self._to_stage(shared, 0)
        shared_last = self._to_stage(shared, S - 1)
        # slice each stage's body params ONCE per batch (the pipe-sharded
        # stack reshards on slicing; per-instruction slicing would repay
        # that transfer every tick)
        bodies = [self._to_stage(
            jax.tree_util.tree_map(lambda a, s=s: a[s], params["body"]), s,
            stacked_src=params["body"])
            for s in range(S)]
        body_of = lambda s: bodies[s]  # noqa: E731
        mb_of = lambda i, s: self._to_stage(jax.tree_util.tree_map(  # noqa: E731,E501
            lambda x: x[i], batch), s)
        base_s = ([self._to_stage(base, s) for s in range(S)]
                  if base is not None else [None] * S)

        schedules = [self.schedule_cls(M, S, s) for s in range(S)]
        streams = [list(s.steps()) for s in schedules]
        n_ticks = max(len(st) for st in streams)
        bufs = [[_Buffer() for _ in range(schedules[s].num_pipe_buffers())]
                for s in range(S)]
        act_wire = [deque() for _ in range(S)]   # edge s-1 -> s
        grad_wire = [deque() for _ in range(S)]  # edge s+1 -> s
        load_count = [0] * S    # LoadMicroBatch FIFO per stage
        recv_count = [0] * S    # RecvActivation FIFO per stage (mb order)
        g_shared_first = None   # shared-param grads from stage 0
        g_shared_last = None    # shared-param grads from stage S-1
        g_body: List[Any] = [None] * S
        losses = []
        dloss = self._to_stage(jnp.asarray(loss_scale, jnp.float32) / M,
                               S - 1)
        stats = {"peak_buffers": [0] * S, "peak_live_bytes": [0] * S,
                 "num_pipe_buffers": [schedules[s].num_pipe_buffers()
                                      for s in range(S)]}
        opt_ran = False

        for tick in range(n_ticks):
            cmds = [streams[s][tick] if tick < len(streams[s]) else []
                    for s in range(S)]
            # phase 1: sends (always reference completed earlier-tick data)
            for s in range(S):
                for c in cmds[s]:
                    buf = bufs[s][c.buffer_id] if isinstance(
                        c, sched.BufferOpInstruction) else None
                    if isinstance(c, sched.SendActivation):
                        # the wire: a real cross-submesh transfer (reference
                        # p2p.py:50 send/recv pair)
                        act_wire[s + 1].append(self._to_stage(buf.y, s + 1))
                        buf.y = None
                    elif isinstance(c, sched.SendGrad):
                        grad_wire[s - 1].append(self._to_stage(buf.gx, s - 1))
                        buf.gx = None
            # phase 2: recv + compute
            for s in range(S):
                for c in cmds[s]:
                    buf = bufs[s][c.buffer_id] if isinstance(
                        c, sched.BufferOpInstruction) else None
                    if isinstance(c, sched.LoadMicroBatch):
                        buf.mb_id = load_count[s]
                        load_count[s] += 1
                    elif isinstance(c, sched.RecvActivation):
                        assert act_wire[s], (
                            f"tick {tick} stage {s}: RecvActivation with an "
                            "empty wire — schedule pairing violated")
                        buf.x = act_wire[s].popleft()
                        buf.mb_id = recv_count[s]
                        recv_count[s] += 1
                    elif isinstance(c, sched.RecvGrad):
                        assert grad_wire[s], (
                            f"tick {tick} stage {s}: RecvGrad with an empty "
                            "wire — schedule pairing violated")
                        buf.gy = grad_wire[s].popleft()
                    elif isinstance(c, sched.ForwardPass):
                        if s == 0:
                            buf.x = mb_of(buf.mb_id, 0)
                            if base is None:
                                y = fns["first_fwd"](shared_first,
                                                     body_of(0), buf.x)
                            else:
                                y = fns["first_fwd"](shared_first,
                                                     body_of(0),
                                                     buf.x, _i32(buf.mb_id),
                                                     base_s[0])
                        elif s < S - 1:
                            if base is None:
                                y = fns["mid_fwd"](body_of(s), buf.x)
                            else:
                                y = fns["mid_fwd"](body_of(s), buf.x,
                                                   _i32(s), _i32(buf.mb_id),
                                                   base_s[s])
                        else:
                            # last stage: loss+backward fuse in BackwardPass
                            # (value_and_grad) — forward here would double
                            # the stage compute under remat-backward
                            continue
                        if s < S - 1:
                            buf.y = y
                    elif isinstance(c, sched.BackwardPass):
                        if s == S - 1:
                            if base is None:
                                loss, gb, gs, gx = fns["last_bwd"](
                                    body_of(s), shared_last, buf.x,
                                    mb_of(buf.mb_id, s), dloss)
                            else:
                                loss, gb, gs, gx = fns["last_bwd"](
                                    body_of(s), shared_last, buf.x,
                                    mb_of(buf.mb_id, s), dloss,
                                    _i32(buf.mb_id), base_s[s])
                            losses.append(loss)
                            g_shared_last = _tree_add(g_shared_last, gs)
                            g_body[s] = _tree_add(g_body[s], gb)
                            buf.gx = gx
                        elif s > 0:
                            if base is None:
                                gb, gx = fns["mid_bwd"](body_of(s), buf.x,
                                                        buf.gy)
                            else:
                                gb, gx = fns["mid_bwd"](
                                    body_of(s), buf.x, buf.gy, _i32(s),
                                    _i32(buf.mb_id), base_s[s])
                            g_body[s] = _tree_add(g_body[s], gb)
                            buf.gx = gx
                        else:
                            if base is None:
                                gs, gb = fns["first_bwd"](
                                    shared_first, body_of(0), buf.x, buf.gy)
                            else:
                                gs, gb = fns["first_bwd"](
                                    shared_first, body_of(0), buf.x, buf.gy,
                                    _i32(buf.mb_id), base_s[0])
                            g_shared_first = _tree_add(g_shared_first, gs)
                            g_body[0] = _tree_add(g_body[0], gb)
                        buf.x = None   # memory release point (1F1B bound)
                        buf.gy = None
                    elif isinstance(c, sched.ReduceTiedGrads):
                        pass  # tied sum falls out of g_shared accumulation
                    elif isinstance(c, sched.ReduceGrads):
                        pass  # data-axis reduction: GSPMD inside stage fns
                    elif isinstance(c, sched.OptimizerStep):
                        opt_ran = True
            # memory accounting at tick boundary
            for s in range(S):
                live = [b for b in bufs[s] if b.live_bytes() > 0]
                stats["peak_buffers"][s] = max(stats["peak_buffers"][s],
                                               len(live))
                stats["peak_live_bytes"][s] = max(
                    stats["peak_live_bytes"][s],
                    sum(b.live_bytes() for b in live))

        assert len(losses) == M, f"expected {M} losses, got {len(losses)}"
        # reassemble on the FULL mesh: per-stage body grads stack back into
        # the pipe-sharded [S, K, ...] layout; the two end stages' shared
        # grads sum (ReduceTiedGrads semantics — the tie-group reduction is
        # this cross-stage add, reference pipe/engine.py:223)
        g_shared = _tree_add(self._to_full(g_shared_first),
                             self._to_full(g_shared_last))
        grads = {
            "pre": g_shared["pre"], "post": g_shared["post"],
            "tied": g_shared["tied"],
            "body": self._from_stages(g_body),
        }
        mean_loss = sum(jax.tree_util.tree_leaves(losses)) / M
        if opt_ran and optimizer_step_fn is not None:
            optimizer_step_fn(grads)
        return mean_loss, grads, stats

    def eval_batch(self, params, batch):
        """Forward-only interpretation of InferenceSchedule."""
        S, M = self.S, self.M
        fns = self._fns(False)
        shared = self._shared_of(params)
        shared_first = self._to_stage(shared, 0)
        shared_last = self._to_stage(shared, S - 1)
        bodies = [self._to_stage(
            jax.tree_util.tree_map(lambda a, s=s: a[s], params["body"]), s,
            stacked_src=params["body"])
            for s in range(S)]
        body_of = lambda s: bodies[s]  # noqa: E731
        mb_of = lambda i, s: self._to_stage(jax.tree_util.tree_map(  # noqa: E731,E501
            lambda x: x[i], batch), s)

        schedules = [sched.InferenceSchedule(M, S, s) for s in range(S)]
        streams = [list(s.steps()) for s in schedules]
        n_ticks = max(len(st) for st in streams)
        bufs = [[_Buffer() for _ in range(schedules[s].num_pipe_buffers())]
                for s in range(S)]
        act_wire = [deque() for _ in range(S)]
        counters = [0] * S
        losses = []
        for tick in range(n_ticks):
            cmds = [streams[s][tick] if tick < len(streams[s]) else []
                    for s in range(S)]
            # forward-only: InferenceSchedule sends in the SAME tick as the
            # forward (unlike TrainSchedule's previous-tick sends), so one
            # ascending-stage pass in cmd order gives correct send/recv
            # pairing — the producer stage always runs before its consumer
            for s in range(S):
                for c in cmds[s]:
                    buf = bufs[s][c.buffer_id] if isinstance(
                        c, sched.BufferOpInstruction) else None
                    if isinstance(c, sched.LoadMicroBatch):
                        buf.mb_id = counters[s]
                        counters[s] += 1
                    elif isinstance(c, sched.RecvActivation):
                        assert act_wire[s], (
                            f"tick {tick} stage {s}: RecvActivation with an "
                            "empty wire — schedule pairing violated")
                        buf.x = act_wire[s].popleft()
                        buf.mb_id = counters[s]
                        counters[s] += 1
                    elif isinstance(c, sched.SendActivation):
                        act_wire[s + 1].append(self._to_stage(buf.y, s + 1))
                        buf.y = None
                    elif isinstance(c, sched.ForwardPass):
                        if s == 0 and S > 1:
                            buf.y = fns["first_fwd_eval"](
                                shared_first, body_of(0),
                                mb_of(buf.mb_id, 0))
                        elif s < S - 1:
                            buf.y = fns["mid_fwd_eval"](body_of(s), buf.x)
                        else:
                            losses.append(fns["last_fwd_eval"](
                                body_of(s), shared_last, buf.x,
                                mb_of(buf.mb_id, s)))
                            buf.x = None
        assert len(losses) == M
        return sum(jax.tree_util.tree_leaves(losses)) / M
