"""Activation checkpointing.

TPU-native replacement for the reference's Megatron-style module
(``runtime/activation_checkpointing/checkpointing.py``: CheckpointFunction:474,
partition_activations:366, CPU checkpointing, RNG-state tracker:121, 881 LoC).

On TPU all of that collapses into ``jax.checkpoint`` (remat) policies:
  * ``partition_activations``  → don't save residuals; recompute from layer
    inputs (policy "nothing") — the sharded-save variant is what GSPMD does
    anyway when activations carry sharding constraints.
  * ``cpu_checkpointing``      → ``save_and_offload_only_these_names`` /
    offload policies (host-offloaded residuals).
  * RNG tracking               → free: jax threads PRNG keys functionally, so
    recomputed dropout sees identical randomness by construction (the whole
    CudaRNGStatesTracker has no analog to port).

``configure()``/``is_configured()`` mirror the reference's module-level API
(checkpointing.py:789) for drop-in familiarity; models consult the config via
``checkpoint_policy``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

_config = None


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    global _config
    if deepspeed_config is not None:
        _config = deepspeed_config.activation_checkpointing_config
    else:
        from deepspeed_tpu.runtime.config import ActivationCheckpointingConfig

        _config = ActivationCheckpointingConfig(
            partition_activations=bool(partition_activations),
            cpu_checkpointing=bool(checkpoint_in_cpu),
            contiguous_memory_optimization=bool(contiguous_checkpointing),
            number_checkpoints=num_checkpoints,
            synchronize_checkpoint_boundary=bool(synchronize),
            profile=bool(profile),
        )


def is_configured() -> bool:
    return _config is not None


def get_config():
    return _config


_POLICIES: dict = {}


def _build_policies():
    global _POLICIES
    if _POLICIES:
        return _POLICIES
    cp = jax.checkpoint_policies
    _POLICIES = {
        None: None,                      # save nothing: classic full remat
        "nothing": None,
        "everything": cp.everything_saveable,
        "dots": cp.dots_saveable,
        "dots_no_batch": cp.dots_with_no_batch_dims_saveable,
        "checkpoint_dots": cp.dots_saveable,
    }
    if hasattr(cp, "save_anything_except_these_names"):
        _POLICIES["offload_dots"] = getattr(
            cp, "offload_dot_with_no_batch_dims", cp.dots_with_no_batch_dims_saveable)
    if hasattr(cp, "save_only_these_names") and \
            hasattr(cp, "save_from_both_policies"):
        # save weight-matmul outputs AND the flash-attention residuals
        # (tagged "flash_res" in ops/flash_attention.py) — backward replays
        # only cheap elementwise work, never the attention kernel. The
        # TPU-native answer to the reference's selective activation
        # checkpointing (runtime/activation_checkpointing.py:474).
        _POLICIES["save_attn"] = cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable,
            cp.save_only_these_names("flash_res"))
    return _POLICIES


def checkpoint_policy(name: Optional[str] = None):
    """Named policy -> jax.checkpoint policy callable (None = save nothing)."""
    policies = _build_policies()
    if name is None and _config is not None:
        if _config.cpu_checkpointing:
            name = "offload_dots" if "offload_dots" in policies else "nothing"
        elif _config.policy:
            name = _config.policy
    if name not in policies:
        raise ValueError(f"unknown remat policy '{name}'; known: {sorted(k for k in policies if k)}")
    return policies[name]


def checkpoint(function: Callable, *args):
    """Drop-in for the reference's ``checkpoint(function, *args)``
    (checkpointing.py:708): returns function(*args) with rematerialisation."""
    return jax.checkpoint(function, policy=checkpoint_policy(None) if _config else None)(*args)


def checkpoint_wrapper(function: Callable, policy: Optional[str] = None,
                       prevent_cse: bool = True, static_argnums=()) -> Callable:
    return jax.checkpoint(function, policy=checkpoint_policy(policy),
                          prevent_cse=prevent_cse, static_argnums=static_argnums)
