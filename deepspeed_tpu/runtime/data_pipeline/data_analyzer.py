"""Offline data analysis for curriculum learning.

Reference analog: ``DataAnalyzer`` (runtime/data_pipeline/data_sampling/
data_analyzer.py:417 LoC): map user metric functions over the whole corpus
(parallelizable by worker shards), then build the two artifacts curriculum
sampling needs per metric:

  * ``<metric>_sample_to_metric.npy`` — metric value per sample index
  * ``<metric>_metric_to_sample.npy`` — sample indices sorted by metric
    (ascending difficulty: the curriculum pool is a prefix of this order)

``DeepSpeedDataSampler`` consumes the sample_to_metric array directly as
its difficulty vector.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.utils.logging import logger


def seqlen_metric(sample) -> float:
    """The stock difficulty metric (reference data_analyzer's seqlen):
    number of tokens in the sample."""
    return float(np.asarray(sample).size)


def vocab_rarity_metric(sample, token_freq: Optional[np.ndarray] = None) -> float:
    """Mean negative log token frequency (reference vocab rarity metric)."""
    arr = np.asarray(sample).reshape(-1)
    if token_freq is None:
        return 0.0
    p = token_freq[arr] / max(token_freq.sum(), 1)
    return float(-np.log(np.maximum(p, 1e-12)).mean())


class DataAnalyzer:
    def __init__(self, dataset, metric_names: Sequence[str] = ("seqlen",),
                 metric_functions: Optional[Sequence[Callable]] = None,
                 output_path: str = "data_analysis",
                 num_workers: int = 1, worker_id: int = 0,
                 num_threads: int = 4):
        self.dataset = dataset
        self.metric_names = list(metric_names)
        if metric_functions is not None:
            self.metric_functions = list(metric_functions)
        elif self.metric_names == ["seqlen"]:
            self.metric_functions = [seqlen_metric]
        else:
            # defaulting every named metric to seqlen would silently produce
            # wrong curricula
            raise ValueError(
                f"metric_functions required for metric_names="
                f"{self.metric_names} (only the default ['seqlen'] has an "
                f"implicit function)")
        assert len(self.metric_names) == len(self.metric_functions)
        self.output_path = output_path
        self.num_workers = max(num_workers, 1)
        self.worker_id = worker_id
        self.num_threads = max(num_threads, 1)

    # ------------------------------------------------------------ map phase
    def _worker_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = min(self.worker_id * per, n)  # trailing workers get empty shards
        return lo, min(lo + per, n)

    def run_map(self) -> Dict[str, str]:
        """Compute this worker's shard of every metric; returns paths of the
        partial files (reference run_map)."""
        lo, hi = self._worker_range()
        os.makedirs(self.output_path, exist_ok=True)
        out = {}
        for name, fn in zip(self.metric_names, self.metric_functions):
            vals = np.empty(hi - lo, np.float64)

            def compute(j):
                vals[j - lo] = fn(self.dataset[j])

            if self.num_threads > 1:
                with cf.ThreadPoolExecutor(self.num_threads) as pool:
                    list(pool.map(compute, range(lo, hi)))
            else:
                for j in range(lo, hi):
                    compute(j)
            path = os.path.join(
                self.output_path,
                f"{name}_worker{self.worker_id}_partial.npy")
            np.save(path, vals)
            out[name] = path
            logger.info(f"data analyzer: {name} [{lo}:{hi}] done")
        return out

    # --------------------------------------------------------- reduce phase
    def run_reduce(self) -> Dict[str, Dict[str, str]]:
        """Merge all workers' partials into the curriculum artifacts
        (reference run_reduce)."""
        out = {}
        for name in self.metric_names:
            parts = []
            for w in range(self.num_workers):
                p = os.path.join(self.output_path,
                                 f"{name}_worker{w}_partial.npy")
                if not os.path.exists(p):
                    raise FileNotFoundError(
                        f"missing partial for worker {w}: {p} (run run_map "
                        f"on every worker first)")
                parts.append(np.load(p))
            sample_to_metric = np.concatenate(parts)
            metric_to_sample = np.argsort(sample_to_metric, kind="stable")
            s2m = os.path.join(self.output_path,
                               f"{name}_sample_to_metric.npy")
            m2s = os.path.join(self.output_path,
                               f"{name}_metric_to_sample.npy")
            np.save(s2m, sample_to_metric)
            np.save(m2s, metric_to_sample)
            out[name] = {"sample_to_metric": s2m, "metric_to_sample": m2s}
        return out

    def run(self) -> Dict[str, Dict[str, str]]:
        """Single-process convenience: map + reduce."""
        self.run_map()
        return self.run_reduce()


def load_difficulties(output_path: str, metric_name: str) -> np.ndarray:
    """The DeepSpeedDataSampler's difficulty vector for a metric."""
    return np.load(os.path.join(output_path,
                                f"{metric_name}_sample_to_metric.npy"))
