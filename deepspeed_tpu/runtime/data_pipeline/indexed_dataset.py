"""Binary indexed dataset — Megatron ``MMapIndexedDataset`` compatible.

Reference analog: ``runtime/data_pipeline/data_sampling/indexed_dataset.py``
(617 LoC, vendored Megatron format): token sequences stored contiguously in
a ``.bin`` file, with a ``.idx`` sidecar holding dtype, per-sequence sizes,
byte pointers, and document boundaries.  Binary compatibility means corpora
tokenized by Megatron/DeepSpeed tooling load directly.

Format (.idx): magic ``MMIDIDX\\x00\\x00`` | uint64 version=1 | uint8 dtype
code | int64 num_sequences | int64 num_documents | int32 sizes[num_seq] |
int64 pointers[num_seq] | int64 doc_idx[num_docs].
"""

from __future__ import annotations

import os
import struct
from functools import lru_cache
from typing import List, Optional, Sequence

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float64, 7: np.float32, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    def __init__(self, out_prefix: str, dtype=np.int32):
        self._prefix = out_prefix
        self._dtype = np.dtype(dtype)
        assert self._dtype in _DTYPE_CODES, f"unsupported dtype {dtype}"
        self._bin = open(data_file_path(out_prefix), "wb")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def finalize(self) -> None:
        self._bin.close()
        if self._doc_idx[-1] != len(self._sizes):
            self._doc_idx.append(len(self._sizes))
        sizes = np.asarray(self._sizes, np.int32)
        itemsize = self._dtype.itemsize
        pointers = np.zeros(len(sizes), np.int64)
        # accumulate in int64: int32 math wraps past 2 GiB of token data
        np.cumsum(sizes[:-1].astype(np.int64) * itemsize, out=pointers[1:])
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _DTYPE_CODES[self._dtype]))
            f.write(struct.pack("<q", len(sizes)))
            f.write(struct.pack("<q", len(self._doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))


class MMapIndexedDataset:
    """Zero-copy reads via np.memmap (reference MMapIndexedDataset)."""

    def __init__(self, prefix: str):
        idx_path = index_file_path(prefix)
        with open(idx_path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{idx_path}: bad magic {magic!r} — not an "
                                 f"MMapIndexedDataset index")
            version, = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"{idx_path}: unsupported version {version}")
            code, = struct.unpack("<B", f.read(1))
            self._dtype = np.dtype(_DTYPES[code])
            n_seq, = struct.unpack("<q", f.read(8))
            n_doc, = struct.unpack("<q", f.read(8))
            offset = f.tell()
        idx_buf = np.memmap(idx_path, mode="r", order="C")
        self._sizes = np.frombuffer(idx_buf, np.int32, count=n_seq,
                                    offset=offset)
        offset += n_seq * 4
        self._pointers = np.frombuffer(idx_buf, np.int64, count=n_seq,
                                       offset=offset)
        offset += n_seq * 8
        self._doc_idx = np.frombuffer(idx_buf, np.int64, count=n_doc,
                                      offset=offset)
        self._data = np.memmap(data_file_path(prefix), mode="r", order="C")

    def __len__(self) -> int:
        return len(self._sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        size = int(self._sizes[i])
        ptr = int(self._pointers[i])
        return np.frombuffer(self._data, self._dtype, count=size, offset=ptr)

    def get(self, i: int, offset: int = 0, length: Optional[int] = None):
        """Sub-sequence read without loading the whole item (reference
        MMapIndexedDataset.get)."""
        size = int(self._sizes[i])
        if not 0 <= offset <= size:
            raise IndexError(f"offset {offset} out of range for sequence {i} "
                             f"of size {size}")
        length = size - offset if length is None else length
        if length < 0 or offset + length > size:
            # a negative frombuffer count means "read to EOF" — would leak
            # other sequences' tokens
            raise IndexError(f"length {length} at offset {offset} exceeds "
                             f"sequence {i} of size {size}")
        ptr = int(self._pointers[i]) + offset * self._dtype.itemsize
        return np.frombuffer(self._data, self._dtype, count=length, offset=ptr)

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    @property
    def doc_idx(self) -> np.ndarray:
        return self._doc_idx

    @property
    def dtype(self):
        return self._dtype

    @staticmethod
    def exists(prefix: str) -> bool:
        return os.path.exists(index_file_path(prefix)) and \
            os.path.exists(data_file_path(prefix))
