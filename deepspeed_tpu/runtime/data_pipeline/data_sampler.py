"""Curriculum data sampler — analog of reference
``runtime/data_pipeline/data_sampling/data_sampler.py`` (DeepSpeedDataSampler
:36): difficulty-indexed sampling for data-efficiency curriculum learning.

Given per-sample difficulty scores (e.g. sequence length, loss from a pilot
run), each epoch samples only from the pool whose difficulty <= the current
curriculum difficulty, growing the pool as training progresses. Deterministic
across processes given the same seed (every host computes identical index
streams — the multi-host analog of the reference's broadcast at
data_sampler.py:224).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:
    def __init__(self, difficulties: Sequence[float], batch_size: int,
                 curriculum: CurriculumScheduler, *, seed: int = 1234,
                 drop_last: bool = True, global_rank: int = 0,
                 data_parallel_size: int = 1):
        self.difficulties = np.asarray(difficulties)
        self.batch_size = batch_size
        self.curriculum = curriculum
        self.seed = seed
        self.drop_last = drop_last
        self.global_rank = global_rank
        self.data_parallel_size = data_parallel_size
        assert batch_size % data_parallel_size == 0, (
            f"batch {batch_size} must divide over dp {data_parallel_size}")
        self.global_step = 0
        # sort once: pool for difficulty d = prefix of this ordering
        self._order = np.argsort(self.difficulties, kind="stable")
        self._sorted_diff = self.difficulties[self._order]

    def _pool(self) -> np.ndarray:
        d = self.curriculum.get_current_difficulty()
        n = int(np.searchsorted(self._sorted_diff, d, side="right"))
        n = max(n, self.batch_size)  # never starve the batch
        return self._order[:min(n, len(self._order))]

    def next_batch_indices(self) -> np.ndarray:
        """Global batch of sample indices for the current step (rank-sliced
        by ``local_slice``)."""
        self.curriculum.update_difficulty(self.global_step)
        pool = self._pool()
        rng = np.random.RandomState(self.seed + self.global_step)
        idx = rng.choice(pool, size=self.batch_size,
                         replace=len(pool) < self.batch_size)
        self.global_step += 1
        return idx

    def local_slice(self, batch_indices: np.ndarray) -> np.ndarray:
        per = self.batch_size // self.data_parallel_size
        r = self.global_rank % self.data_parallel_size
        return batch_indices[r * per:(r + 1) * per]

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.local_slice(self.next_batch_indices())

    def state_dict(self) -> Dict:
        return {"global_step": self.global_step,
                "curriculum": self.curriculum.state_dict()}

    def load_state_dict(self, sd: Dict):
        self.global_step = sd["global_step"]
        self.curriculum.load_state_dict(sd["curriculum"])
