"""Random layerwise token dropping (random-LTD) — analog of reference
``runtime/data_pipeline/data_routing/`` (basic_layer.py RandomLayerTokenDrop,
scheduler.py RandomLTDScheduler) + the ``csrc/random_ltd`` CUDA kernels
(token_sort.cu / gather_scatter.cu, SURVEY §2.4).

The CUDA token gather/scatter kernels become jnp takes — XLA fuses them into
the surrounding layers on TPU; static shapes are preserved by keeping the
kept-token count a python int per compiled step (the scheduler changes it
across steps, which recompiles on a small ladder of sizes, matching how the
reference reserves per-seqlen kernels).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def gather_tokens(x: jax.Array, indices: jax.Array) -> jax.Array:
    """x: [B, T, D]; indices: [B, T_keep] → [B, T_keep, D]
    (csrc/random_ltd/gather_scatter.cu gather analog)."""
    return jnp.take_along_axis(x, indices[..., None], axis=1)


def scatter_tokens(full: jax.Array, kept: jax.Array, indices: jax.Array) -> jax.Array:
    """Write ``kept`` back into ``full`` at ``indices`` (scatter analog)."""
    b, tk = indices.shape
    bidx = jnp.arange(b)[:, None]
    return full.at[bidx, indices].set(kept)


def sample_token_indices(rng, batch: int, seq_len: int, keep: int) -> jax.Array:
    """Sorted random subset of token positions per batch row (the token_sort.cu
    analog: sorted so relative order — and causality — is preserved)."""
    noise = jax.random.uniform(rng, (batch, seq_len))
    idx = jnp.argsort(noise, axis=-1)[:, :keep]
    return jnp.sort(idx, axis=-1)


def random_ltd_token_drop(x: jax.Array, rng, keep: int) -> Tuple[jax.Array, jax.Array]:
    """Drop tokens for one layer: returns (kept_tokens, indices)."""
    b, t = x.shape[0], x.shape[1]
    idx = sample_token_indices(rng, b, t, keep)
    return gather_tokens(x, idx), idx


class RandomLTDScheduler:
    """Kept-token schedule (reference data_routing/scheduler.py): linear ramp
    from ``start_seq`` to ``full_seq`` over ``total_steps``, stepping in
    ``increment`` granules to bound recompiles."""

    def __init__(self, config: Dict):
        cfg = config.get("random_ltd", config)
        self.start_seq = cfg.get("random_ltd_schedule", {}).get(
            "min_value", cfg.get("min_value", 128))
        self.full_seq = cfg.get("random_ltd_schedule", {}).get(
            "max_value", cfg.get("max_value", 512))
        sched = cfg.get("random_ltd_schedule", cfg)
        self.total_steps = sched.get("schedule_config", sched).get(
            "total_layer_tokens_steps", sched.get("total_steps", 1000))
        self.increment = sched.get("schedule_config", sched).get(
            "seq_per_step", sched.get("increment", 16))
        self.current_seq = self.start_seq

    def update_seq(self, global_step: int) -> int:
        frac = min(global_step / max(self.total_steps, 1), 1.0)
        seq = self.start_seq + (self.full_seq - self.start_seq) * frac
        seq = int(seq // self.increment) * self.increment
        self.current_seq = max(self.start_seq, min(seq, self.full_seq))
        return self.current_seq

    def get_current_seq(self) -> int:
        return self.current_seq

    def state_dict(self) -> Dict:
        return {"current_seq": self.current_seq}

    def load_state_dict(self, sd: Dict):
        self.current_seq = sd["current_seq"]
