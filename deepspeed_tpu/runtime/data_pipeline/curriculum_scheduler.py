"""Curriculum scheduler — analog of reference
``deepspeed/runtime/data_pipeline/curriculum_scheduler.py`` (legacy
curriculum, engine.py:1653 injects ``curriculum_seqlen``).

Difficulty schedules: fixed_linear, fixed_root, fixed_discrete, custom —
same config schema as the reference (schedule_type + schedule_config with
min/max difficulty, total_curriculum_step, difficulty_step, root_degree or
discrete difficulty/max_step lists).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional


FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config: Dict):
        self.state: Dict = {}
        assert "curriculum_type" in config or "schedule_type" in config, (
            "curriculum config needs schedule_type/curriculum_type")
        self.curriculum_type = config.get("schedule_type",
                                          config.get("curriculum_type"))
        cfg = config.get("schedule_config", config)
        self.min_difficulty = cfg.get("min_difficulty", 1)
        self.max_difficulty = cfg.get("max_difficulty", 1)
        self.current_difficulty = self.min_difficulty
        self._custom_fn: Optional[Callable[[int], int]] = None

        if self.curriculum_type == FIXED_LINEAR:
            self.total_step = cfg["total_curriculum_step"]
            self.difficulty_step = cfg.get("difficulty_step", 1)
        elif self.curriculum_type == FIXED_ROOT:
            self.total_step = cfg["total_curriculum_step"]
            self.difficulty_step = cfg.get("difficulty_step", 1)
            self.root_degree = cfg.get("root_degree", 2)
        elif self.curriculum_type == FIXED_DISCRETE:
            self.difficulties = cfg["difficulty"]
            self.max_steps = cfg["max_step"]
            assert len(self.difficulties) == len(self.max_steps) + 1, (
                "fixed_discrete needs len(difficulty) == len(max_step)+1")
        elif self.curriculum_type == CUSTOM:
            pass
        else:
            raise ValueError(f"unknown curriculum schedule {self.curriculum_type!r}")

    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        assert self.curriculum_type == CUSTOM
        self._custom_fn = fn

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def set_current_difficulty(self, difficulty: int):
        self.current_difficulty = difficulty

    def update_difficulty(self, global_steps: int) -> int:
        ct = self.curriculum_type
        if ct == FIXED_LINEAR:
            d = self.min_difficulty + (
                (self.max_difficulty - self.min_difficulty) *
                min(global_steps / self.total_step, 1.0))
            d = int(d // self.difficulty_step) * self.difficulty_step
        elif ct == FIXED_ROOT:
            frac = min(global_steps / self.total_step, 1.0) ** (1.0 / self.root_degree)
            d = self.min_difficulty + (self.max_difficulty - self.min_difficulty) * frac
            d = int(d // self.difficulty_step) * self.difficulty_step
        elif ct == FIXED_DISCRETE:
            d = self.difficulties[-1]
            for diff, step in zip(self.difficulties, self.max_steps):
                if global_steps < step:
                    d = diff
                    break
        else:  # custom
            assert self._custom_fn is not None, "custom curriculum needs a fn"
            d = self._custom_fn(global_steps)
        self.current_difficulty = max(self.min_difficulty,
                                      min(int(d), self.max_difficulty))
        return self.current_difficulty

    def get_difficulty(self, global_steps: int) -> int:
        return self.update_difficulty(global_steps)

    def state_dict(self) -> Dict:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict):
        self.current_difficulty = sd["current_difficulty"]
