from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
    RandomLTDScheduler,
    gather_tokens,
    random_ltd_token_drop,
    scatter_tokens,
)

__all__ = ["CurriculumScheduler", "DeepSpeedDataSampler", "RandomLTDScheduler",
           "gather_tokens", "scatter_tokens", "random_ltd_token_drop"]
