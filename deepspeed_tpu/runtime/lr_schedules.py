"""LR schedules — analog of reference ``deepspeed/runtime/lr_schedules.py``
(WarmupLR, WarmupDecayLR, WarmupCosineLR, OneCycle, LRRangeTest; 763 LoC).

Schedules are host-side Python (the LR enters the compiled step as a traced
scalar, so stepping never recompiles). API mirrors torch schedulers:
``step()``, ``get_lr()``, ``get_last_lr()``, ``state_dict()``/``load_state_dict()``.
"""

from __future__ import annotations

import math
from typing import List, Optional

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


class _LRSchedule:
    def __init__(self, optimizer, last_batch_iteration: int = -1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration
        self._last_lr: List[float] = [0.0]

    def get_lr(self) -> List[float]:
        raise NotImplementedError

    def get_last_lr(self) -> List[float]:
        return self._last_lr

    def step(self, last_batch_iteration: Optional[int] = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = self.get_lr()
        if self.optimizer is not None and hasattr(self.optimizer, "lr"):
            self.optimizer.lr = self._last_lr[0]
        return self._last_lr[0]

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]
        self._last_lr = self.get_lr()


class WarmupLR(_LRSchedule):
    """Linear/log warmup from warmup_min_lr to warmup_max_lr, then constant
    (reference lr_schedules.py WarmupLR)."""

    def __init__(self, optimizer=None, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = WARMUP_LOG_RATE, last_batch_iteration: int = -1):
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)
        super().__init__(optimizer, last_batch_iteration)

    def _get_gamma(self) -> float:
        if self.last_batch_iteration < self.warmup_num_steps:
            if self.warmup_type == WARMUP_LOG_RATE:
                return self.inverse_log_warm_up * math.log(self.last_batch_iteration + 1)
            return self.last_batch_iteration / self.warmup_num_steps
        return 1.0

    def get_lr(self) -> List[float]:
        if self.last_batch_iteration < 0:
            return [0.0]
        gamma = self._get_gamma()
        return [self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * gamma]


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to zero at total_num_steps."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = WARMUP_LOG_RATE, last_batch_iteration: int = -1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)

    def _get_gamma(self) -> float:
        if self.last_batch_iteration < self.warmup_num_steps:
            return super()._get_gamma()
        return max(
            0.0,
            float(self.total_num_steps - self.last_batch_iteration) /
            float(max(1.0, self.total_num_steps - self.warmup_num_steps)))


class WarmupCosineLR(WarmupLR):
    """Warmup then cosine decay to cos_min_ratio * warmup_max_lr."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000, warmup_min_ratio: float = 0.0,
                 warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0001,
                 warmup_type: str = WARMUP_LINEAR_RATE, warmup_max_lr: float = 0.001,
                 last_batch_iteration: int = -1):
        self.total_num_steps = total_num_steps
        self.cos_min_ratio = cos_min_ratio
        super().__init__(optimizer, warmup_min_ratio * warmup_max_lr, warmup_max_lr,
                         warmup_num_steps, warmup_type, last_batch_iteration)

    def _get_gamma(self) -> float:
        if self.last_batch_iteration < self.warmup_num_steps:
            return super()._get_gamma()
        progress = (self.last_batch_iteration - self.warmup_num_steps) / max(
            1, self.total_num_steps - self.warmup_num_steps)
        progress = min(1.0, progress)
        cosine = 0.5 * (1.0 + math.cos(math.pi * progress))
        return self.cos_min_ratio + (1 - self.cos_min_ratio) * cosine


class OneCycle(_LRSchedule):
    """1-cycle policy (reference lr_schedules.py OneCycle): lr ramps
    min→max→min over cycle then decays."""

    def __init__(self, optimizer=None, cycle_min_lr: float = 0.0001, cycle_max_lr: float = 0.001,
                 decay_lr_rate: float = 0.0, cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0, cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0, cycle_momentum: bool = False,
                 cycle_min_mom: float = 0.8, cycle_max_mom: float = 0.9,
                 decay_mom_rate: float = 0.0, last_batch_iteration: int = -1):
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = cycle_first_step_size
        self.second_size = cycle_second_step_size or cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.total_size = self.first_size + self.second_size
        super().__init__(optimizer, last_batch_iteration)

    def get_lr(self) -> List[float]:
        it = max(self.last_batch_iteration, 0)
        if it <= self.total_size:
            if it <= self.first_size:
                scale = it / self.first_size
            else:
                scale = 1.0 - (it - self.first_size) / self.second_size
            lr = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * scale
        else:
            extra = it - self.total_size
            if self.decay_step_size > 0:
                decay = (extra // self.decay_step_size) * self.decay_lr_rate
            else:
                decay = extra * self.decay_lr_rate
            lr = max(self.cycle_min_lr / (1.0 + decay), 0.0) if self.decay_lr_rate else self.cycle_min_lr
        return [lr]


class LRRangeTest(_LRSchedule):
    """LR range test (reference lr_schedules.py LRRangeTest)."""

    def __init__(self, optimizer=None, lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000, lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False, last_batch_iteration: int = -1):
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        super().__init__(optimizer, last_batch_iteration)

    def get_lr(self) -> List[float]:
        it = max(self.last_batch_iteration, 0)
        if self.staircase:
            interval = float(it // self.step_size)
        else:
            interval = it / self.step_size
        return [self.min_lr * (1 + interval * self.step_rate)]


SCHEDULE_REGISTRY = {
    "WarmupLR": WarmupLR,
    "WarmupDecayLR": WarmupDecayLR,
    "WarmupCosineLR": WarmupCosineLR,
    "OneCycle": OneCycle,
    "LRRangeTest": LRRangeTest,
}

VALID_LR_SCHEDULES = list(SCHEDULE_REGISTRY)


def build_lr_scheduler(name: str, params: dict, optimizer=None) -> _LRSchedule:
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(f"unknown lr schedule '{name}'; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_REGISTRY[name](optimizer=optimizer, **params)
