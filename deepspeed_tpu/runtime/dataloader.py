"""Data loading.

Analog of reference ``runtime/dataloader.py`` (DeepSpeedDataLoader +
DistributedSampler wiring, RepeatingLoader). TPU-native differences: JAX is
single-controller per host, so the "distributed sampler" shards batches by
``jax.process_index()`` across hosts; within a host the engine shards the
global batch across devices via NamedSharding (no per-device loader).

Sources supported: python iterables/generators yielding dict/tuple batches of
numpy/jnp arrays, torch Datasets (indexed), and callables. Curriculum /
data-efficiency sampling plugs in via ``deepspeed_tpu.runtime.data_pipeline``.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference RepeatingLoader)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batches an indexable dataset into per-step numpy batches.

    - ``batch_size`` is the *micro* batch per data-parallel replica times the
      local replica count — i.e. the per-process slice of the global batch.
    - multi-host: each process reads its own shard (rank-strided, like the
      reference's DistributedSampler).

    Deterministic resume (ISSUE 10): the batch stream is a pure function of
    ``(seed, epoch, in-epoch offset)``. ``state_dict()/load_state_dict()``
    capture/restore that triple, and the engine persists it inside every
    checkpoint's ``__meta__`` — so a crash-restart or an anomaly
    rewind-and-skip replays *exactly* the batch stream an uninterrupted run
    would have seen. Each epoch reshuffles with ``seed + epoch`` and the
    loader auto-advances ``epoch`` on exhaustion, so wrap-around (via
    :class:`RepeatingLoader`) stays deterministic too.

    NOTE the contract change this implies: the loader is a
    position-tracking STREAM, not a restartable sequence. Every batch
    pulled — including via an abandoned partial iteration — advances the
    position that ``state_dict()`` reports and the next ``__iter__``
    resumes from; don't iterate the same instance from two places. To
    re-read from a known point, call ``set_epoch(e)`` (top of epoch
    ``e``) or ``load_state_dict``.
    """

    def __init__(self, dataset, batch_size: int, *, collate_fn: Optional[Callable] = None,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 num_replicas: Optional[int] = None, rank: Optional[int] = None,
                 data_sampler=None):
        import jax

        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.num_replicas = num_replicas if num_replicas is not None else jax.process_count()
        self.rank = rank if rank is not None else jax.process_index()
        self.epoch = 0
        self.data_sampler = data_sampler
        self._offset = 0  # batches already yielded in the current epoch

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self._offset = 0

    def supports_deterministic_resume(self) -> bool:
        """The (seed, epoch, offset) triple pins the stream only when this
        loader generates its own index order; an external ``data_sampler``
        is re-pulled every epoch and may not replay (stateful/stochastic
        samplers), so its position cannot be promised across a restart."""
        return self.data_sampler is None

    def state_dict(self) -> dict:
        """Resume state: JSON-serializable, a few ints — cheap enough to
        ride in every checkpoint's ``__meta__``. The identity fields
        (batch_size/num_samples/replica/shuffle) are not restored; the
        checkpoint loader compares them against the live loader so a
        warm-start onto a DIFFERENT dataset never inherits a stale
        mid-stream position."""
        return {"seed": int(self.seed), "epoch": int(self.epoch),
                "offset": int(self._offset),
                "batch_size": int(self.batch_size),
                "num_samples": int(len(self.dataset)),
                "num_replicas": int(self.num_replicas),
                "rank": int(self.rank),
                "shuffle": bool(self.shuffle)}

    def load_state_dict(self, state: dict):
        """Pin the stream position; takes effect at the next ``__iter__``
        (generators are lazy, so a ``RepeatingLoader`` built before this
        call still honors it as long as nothing was pulled yet — the
        engine rebuilds its iterator after a checkpoint load regardless)."""
        self.seed = int(state["seed"])
        self.epoch = int(state["epoch"])
        self._offset = int(state["offset"])

    def resume_state_matches(self, state: dict) -> bool:
        """Does ``state`` describe THIS data pipeline? Identity fields
        saved alongside the position must agree (fields absent from older
        checkpoints are not compared)."""
        current = self.state_dict()
        return all(state[k] == current[k]
                   for k in ("batch_size", "num_samples", "num_replicas",
                             "rank", "shuffle") if k in state)

    def __len__(self):
        n = len(self.dataset) // self.num_replicas
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        if self.data_sampler is not None:
            indices = list(self.data_sampler)
        else:
            indices = np.arange(n)
            if self.shuffle:
                rng = np.random.RandomState(self.seed + self.epoch)
                rng.shuffle(indices)
        indices = indices[self.rank::self.num_replicas]
        batch = []
        for idx in indices[self._offset * self.batch_size:]:
            batch.append(self.dataset[int(idx)])
            if len(batch) == self.batch_size:
                self._offset += 1
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            self._offset += 1
            yield self.collate_fn(batch)
        # epoch exhausted: advance so the next pass (RepeatingLoader
        # restart) reshuffles deterministically with seed + epoch
        self.epoch += 1
        self._offset = 0


def default_collate(samples):
    """Stack a list of samples (dicts / tuples / arrays) into numpy batches."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples]) for i in range(len(first)))
    arr = np.stack([np.asarray(s) for s in samples])
    return arr


def build_dataloader(dataset, batch_size: int, config=None, **kw) -> DeepSpeedDataLoader:
    drop_last = kw.pop("drop_last", None)
    if drop_last is None and config is not None:
        drop_last = config.dataloader_drop_last
    return DeepSpeedDataLoader(dataset, batch_size, drop_last=bool(drop_last), **kw)
