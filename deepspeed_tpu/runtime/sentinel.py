"""Training anomaly sentinel (ISSUE 10).

Long pretraining runs are dominated not by crashes (PR 1's territory) but by
*soft* failures: loss spikes, nonfinite gradients outside the fp16
loss-scaler path, and silent data corruption. PaLM (Chowdhery et al., 2022)
recovered from spikes by rewinding to a checkpoint and skipping the
offending batches; MegaScale (Jiang et al., 2024) showed SDC detection plus
automated recovery is what keeps goodput high at scale. This module is the
host-side half of that machinery:

  * :class:`RollingRobustStats` — fixed-window robust (median/MAD) z-score
    over a scalar series. Median/MAD instead of mean/std so a spike cannot
    inflate its own detection threshold.
  * :class:`TrainingSentinel` — classifies each step's (loss, grad-norm,
    overflow-flag) observation into the anomaly taxonomy: ``overflow``
    (fp16 loss-scaler handled it), ``nonfinite`` (NaN/Inf loss or grads —
    on bf16/fp32 the engine's ``check_finite_grads`` guard skipped the
    update), ``spike`` (finite but a robust-z outlier), ``divergence``
    (``divergence_patience`` consecutive spikes).
  * :func:`sdc_audit` — cross-data-parallel-replica checksum agreement:
    devices holding the same logical shard of a replicated/sharded array
    are bit-identical by construction, so any checksum disagreement is
    silent data corruption; majority vote localizes the deviating device.
  * :func:`step_replay_probe` — single-host determinism probe: the same
    compiled step from the same state must produce bit-identical results;
    a mismatch is flaky hardware.

Everything here is host logic over already-fetched scalars — the engine
feeds the sentinel at its existing telemetry fences so detection costs no
extra device syncs; the device-side half (nonfinite flags inside the
compiled step) lives in ``runtime/engine.py`` / ``runtime/precision.py``.
"""

from __future__ import annotations

import math
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np


class AnomalyClass:
    """Anomaly taxonomy (see module docstring)."""

    OVERFLOW = "overflow"      # fp16 dynamic-loss-scale overflow (handled)
    NONFINITE = "nonfinite"    # NaN/Inf loss or grads outside the scaler
    SPIKE = "spike"            # finite robust-z outlier in loss/grad-norm
    DIVERGENCE = "divergence"  # sustained spikes (patience exceeded)
    SDC = "sdc"                # cross-replica checksum disagreement
    REPLAY = "replay"          # step-replay determinism mismatch

    # classes where the data window is suspect: recovery skips the batches
    # between the rewind target and the anomaly (PaLM-style). SDC/replay
    # are hardware faults — the data is fine, so recovery replays it.
    DATA_CLASSES = (NONFINITE, SPIKE, DIVERGENCE)


class TrainingAnomaly(NamedTuple):
    cls: str
    step: int
    value: float
    zscore: float
    detail: str


class TrainingAnomalyError(RuntimeError):
    """A confirmed training anomaly the engine could not auto-recover from
    (no engine-owned dataloader / checkpoint dir, or ``on_anomaly='raise'``)."""

    def __init__(self, anomaly: TrainingAnomaly, msg: Optional[str] = None):
        self.anomaly = anomaly
        super().__init__(
            msg or f"training anomaly: {anomaly.cls} at step {anomaly.step} "
                   f"(value={anomaly.value:.6g}, z={anomaly.zscore:.2f}): "
                   f"{anomaly.detail}")


class RewindBudgetExceededError(TrainingAnomalyError):
    """The rewind budget (rolling window, ElasticAgent semantics) is spent —
    a persistently poisoned shard or failing host must not livelock the job
    in a rewind loop; fail loudly for the operator / elastic agent."""


class RollingRobustStats:
    """Fixed-window series with robust z-scores: z = 0.6745·(v−median)/MAD.

    The 0.6745 factor makes the MAD a consistent σ estimator under
    normality, so thresholds read in 'sigmas'. The MAD is floored
    (relative to |median|) so a near-constant history cannot turn noise
    into infinite z-scores."""

    def __init__(self, window: int = 64):
        self.values: deque = deque(maxlen=max(int(window), 2))

    def __len__(self) -> int:
        return len(self.values)

    def push(self, v: float) -> None:
        self.values.append(float(v))

    def median_mad(self) -> Tuple[float, float]:
        arr = np.asarray(self.values, dtype=np.float64)
        med = float(np.median(arr))
        mad = float(np.median(np.abs(arr - med)))
        return med, max(mad, 1e-3 * abs(med), 1e-12)

    def zscore(self, v: float) -> float:
        if not self.values:
            return 0.0
        med, mad = self.median_mad()
        return 0.6745 * (float(v) - med) / mad

    def reset(self) -> None:
        self.values.clear()


class TrainingSentinel:
    """Per-step anomaly classifier over (loss, grad-norm, overflow) reads.

    ``observe`` returns a :class:`TrainingAnomaly` for anomalous steps and
    ``None`` for clean ones. Anomalous values are NOT pushed into the
    rolling history (a spike must not raise its own baseline); clean
    values are. ``counts`` accumulates per-class totals for telemetry.
    Host-only: no jax imports, usable from any thread."""

    def __init__(self, *, window: int = 64, min_history: int = 8,
                 spike_zscore: float = 8.0, divergence_patience: int = 4,
                 fp16: bool = False):
        self.loss_stats = RollingRobustStats(window)
        self.norm_stats = RollingRobustStats(window)
        self.min_history = max(int(min_history), 2)
        self.spike_zscore = float(spike_zscore)
        self.divergence_patience = max(int(divergence_patience), 2)
        self.fp16 = fp16
        self.consecutive_spikes = 0
        self.counts: Dict[str, int] = {}

    def _anomaly(self, cls: str, step: int, value: float, z: float,
                 detail: str) -> TrainingAnomaly:
        self.counts[cls] = self.counts.get(cls, 0) + 1
        return TrainingAnomaly(cls, step, float(value), float(z), detail)

    def observe(self, step: int, loss: float, grad_norm: float,
                overflow: bool = False) -> Optional[TrainingAnomaly]:
        loss = float(loss)
        grad_norm = float(grad_norm)
        if overflow:
            if self.fp16:
                # the dynamic loss scaler already skipped the update and
                # halved the scale — classified + counted, not actionable
                return self._anomaly(AnomalyClass.OVERFLOW, step, loss, 0.0,
                                     "fp16 loss-scale overflow")
            return self._anomaly(AnomalyClass.NONFINITE, step, loss, 0.0,
                                 "nonfinite grads (finite-grad guard)")
        if not math.isfinite(loss) or not math.isfinite(grad_norm):
            return self._anomaly(AnomalyClass.NONFINITE, step, loss, 0.0,
                                 f"loss={loss} grad_norm={grad_norm}")
        z_loss = self.loss_stats.zscore(loss)
        z_norm = self.norm_stats.zscore(grad_norm)
        warmed = (len(self.loss_stats) >= self.min_history)
        if warmed and max(z_loss, z_norm) > self.spike_zscore:
            self.consecutive_spikes += 1
            z = max(z_loss, z_norm)
            which = "loss" if z_loss >= z_norm else "grad_norm"
            if self.consecutive_spikes >= self.divergence_patience:
                return self._anomaly(
                    AnomalyClass.DIVERGENCE, step, loss, z,
                    f"{self.consecutive_spikes} consecutive {which} spikes")
            return self._anomaly(AnomalyClass.SPIKE, step, loss, z,
                                 f"{which} robust-z {z:.1f} > "
                                 f"{self.spike_zscore}")
        self.consecutive_spikes = 0
        self.loss_stats.push(loss)
        self.norm_stats.push(grad_norm)
        return None

    def reset(self) -> None:
        """Discard all history — for the CALLER's intentional regime
        changes only (e.g. a scheduled LR jump that legitimately shifts
        the loss distribution). The engine deliberately does NOT call
        this on anomaly rewind: a rewind restores the pre-anomaly regime,
        so the existing history is the correct baseline, and resetting
        would open a min_history blind spot exactly where a widened
        second skip may be needed."""
        self.loss_stats.reset()
        self.norm_stats.reset()
        self.consecutive_spikes = 0


# --------------------------------------------------------------- SDC audits
class SDCAuditResult(NamedTuple):
    ok: bool
    suspects: Tuple[int, ...]        # device ids, worst offender first
    mismatched_groups: int           # (leaf, shard-index) groups disagreeing
    n_groups: int                    # replica groups compared (>1 copy each)


def _path_str(path) -> str:
    import jax

    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def replica_checksums(tree) -> Dict[Tuple[str, Tuple], Dict[int, int]]:
    """Per-replica crc32s: ``(leaf path, shard index) -> {device_id: crc}``.

    Devices whose shards cover the same global index range of the same
    array hold replicas of that range (fully replicated arrays are the
    all-devices special case) — their bytes must agree bit-exactly."""
    import jax

    out: Dict[Tuple[str, Tuple], Dict[int, int]] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if not hasattr(leaf, "addressable_shards"):
            continue
        key0 = _path_str(path)
        for sh in leaf.addressable_shards:
            idx = tuple((s.start, s.stop, s.step) for s in sh.index)
            crc = zlib.crc32(np.ascontiguousarray(
                np.asarray(sh.data)).tobytes()) & 0xFFFFFFFF
            out.setdefault((key0, idx), {})[sh.device.id] = crc
    return out


def sdc_audit(tree) -> SDCAuditResult:
    """Cross-replica checksum agreement over ``tree`` (params and/or
    optimizer state). Majority vote per disagreeing group names the
    deviating device(s); a device deviating in the most groups is the
    prime suspect (a real bit-flip corrupts one replica's copy of one
    array — it shows up as exactly that device disagreeing)."""
    groups = replica_checksums(tree)
    suspect_hits: Dict[int, int] = {}
    mismatched = 0
    compared = 0
    for _, per_dev in groups.items():
        if len(per_dev) < 2:
            continue
        compared += 1
        crcs = list(per_dev.values())
        if len(set(crcs)) == 1:
            continue
        mismatched += 1
        counts: Dict[int, int] = {}
        for c in crcs:
            counts[c] = counts.get(c, 0) + 1
        majority = max(counts, key=lambda c: counts[c])
        for dev, c in per_dev.items():
            if c != majority:
                suspect_hits[dev] = suspect_hits.get(dev, 0) + 1
    suspects = tuple(sorted(suspect_hits, key=lambda d: -suspect_hits[d]))
    return SDCAuditResult(ok=mismatched == 0, suspects=suspects,
                          mismatched_groups=mismatched, n_groups=compared)


def _tree_digest(tree) -> int:
    """crc32 over every leaf's device_get bytes — bit-exact equality probe."""
    import jax

    crc = 0
    for leaf in jax.tree_util.tree_leaves(jax.device_get(tree)):
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(leaf)).tobytes(),
                         crc)
    return crc & 0xFFFFFFFF


def step_replay_probe(step_fn: Callable, state, state_shardings,
                      args: Tuple = ()) -> Tuple[bool, str]:
    """Run ``step_fn(state, *args)`` twice from bit-identical copies of
    ``state`` and compare the outputs bit-exactly. A compiled XLA program
    is deterministic, so any disagreement is hardware silent data
    corruption (flaky ALU / HBM). Copies go through a host round-trip so
    a ``donate_argnums`` step consumes the copy, never the live state.
    Returns ``(ok, detail)``."""
    import jax

    host = jax.device_get(state)
    digests: List[int] = []
    for _ in range(2):
        replica = jax.device_put(host, state_shardings)
        out = step_fn(replica, *args)
        digests.append(_tree_digest(out))
    ok = digests[0] == digests[1]
    return ok, ("ok" if ok else
                f"replay digests differ: {digests[0]:#010x} vs "
                f"{digests[1]:#010x}")
