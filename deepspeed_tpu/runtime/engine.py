"""Training engine (L4).

TPU-native re-design of the reference ``DeepSpeedEngine``
(runtime/engine.py:181, 3267 LoC). The reference wraps a torch nn.Module and
drives forward/backward/step imperatively with grad hooks firing collectives;
here the entire step — microbatch scan (grad accumulation), loss scaling,
mixed-precision casts, ZeRO collectives, overflow check, clip, optimizer
update, loss-scale adjustment — is ONE compiled XLA program built from the
PartitionPlan's shardings. XLA schedules the reduce-scatters/all-gathers the
reference hand-buckets (stage_1_and_2.py average_tensor:894, stage3.py
__reduce_and_partition_ipg_grads:1045).

API parity (reference names in parens):
    engine(batch) / engine.forward(batch)   — compute loss (+cache grads)
    engine.backward(loss)                   — accumulate grads (backward:1755)
    engine.step()                           — optimizer step at gas boundary
                                              (step:1951, _take_model_step:1886)
    engine.train_batch(data_iter)           — fused full step (PipelineEngine
                                              train_batch:285 shape, but valid
                                              for every topology here)
    engine.eval_batch(batch)                — no-grad loss
    engine.save_checkpoint / load_checkpoint
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, Iterator, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.ops.adam import build_optimizer
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.lr_schedules import build_lr_scheduler
from deepspeed_tpu.runtime.precision import (
    DynamicLossScaler,
    LossScalerState,
    StaticLossScaler,
    clip_grads_by_global_norm,
    create_loss_scaler,
    global_grad_norm,
    has_inf_or_nan,
)
from deepspeed_tpu.runtime.zero.partition import PartitionPlan
from deepspeed_tpu.utils import groups as groups_mod
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (
    BACKWARD_GLOBAL_TIMER,
    FORWARD_GLOBAL_TIMER,
    STEP_GLOBAL_TIMER,
    TRAIN_BATCH_TIMER,
    SynchronizedWallClockTimer,
    ThroughputTimer,
)


class TrainState(NamedTuple):
    params: Any            # fp32 master params (sharded per plan)
    opt_state: Any
    scaler: LossScalerState
    global_step: jax.Array


class DeepSpeedEngine:
    def __init__(self, model, config: Union[DeepSpeedConfig, dict, str], *,
                 optimizer=None, lr_scheduler=None, training_data=None,
                 collate_fn=None, topology=None, init_rng=None, dont_change_device=False):
        if not isinstance(config, DeepSpeedConfig):
            config = DeepSpeedConfig(config)
        self.config = config
        self._config = config  # reference attribute name
        self.module = model
        self.accelerator = get_accelerator()

        # ---- topology / groups (engine _configure_distributed_model analog)
        if topology is None:
            topology = groups_mod.initialize(
                tp_size=config.tensor_parallel.tp_size,
                pp_size=config.pipeline.stages,
                ep_size=config.expert_parallel.ep_size,
                sp_size=config.sequence_parallel.sp_size,
            )
        else:
            groups_mod.initialize(topology)
        self.topology = topology
        self.mesh = topology.mesh

        # ---- precision policy
        self.fp16_enabled = config.fp16_enabled
        self.bfloat16_enabled = config.bfloat16_enabled
        if self.fp16_enabled:
            self.compute_dtype = jnp.float16
            self.loss_scaler = create_loss_scaler(config.fp16_config)
        elif self.bfloat16_enabled:
            self.compute_dtype = jnp.bfloat16
            self.loss_scaler = StaticLossScaler(1.0)
        else:
            self.compute_dtype = jnp.float32
            self.loss_scaler = StaticLossScaler(1.0)
        self.dynamic_loss_scale = isinstance(self.loss_scaler, DynamicLossScaler)

        # ---- partition plan (ZeRO + TP declarative shardings)
        self.zero_stage = config.zero_optimization_stage
        self.plan = PartitionPlan(
            topology=topology,
            zero_stage=self.zero_stage,
            param_persistence_threshold=config.zero_config.param_persistence_threshold,
        )
        self.logical_axes = model.logical_axes() if hasattr(model, "logical_axes") else None

        # ---- offload: optimizer state / master params to host memory
        zc = config.zero_config
        self.offload_optimizer = bool(
            zc.offload_optimizer and zc.offload_optimizer.device != "none")

        # ---- optimizer (reference _configure_optimizer:1137)
        if optimizer is None and config.optimizer_name is not None:
            optimizer = build_optimizer(config.optimizer_name, config.optimizer_params)
        if optimizer is None:
            optimizer = build_optimizer("adam", {"lr": 1e-3})
        from deepspeed_tpu.ops.onebit import _OnebitBase

        self._onebit_compressed = False
        if isinstance(optimizer, _OnebitBase) and optimizer.with_compression:
            # true 1-bit comm needs LOCAL (unreduced) grads: the engine runs
            # the whole step under shard_map over the data axis so the
            # optimizer's compressed momentum sync REPLACES the grad
            # allreduce (reference disables backward allreduce for 1-bit
            # optimizers the same way). Only meaningful on a pure-DP stage-0
            # layout — other topologies fall back to exact math.
            pure_dp = (topology.data_parallel_size > 1 and
                       all(topology.get_dim(a) == 1
                           for a in ("model", "seq", "pipe", "expert")))
            if pure_dp and self.zero_stage == 0 and not \
                    self.offload_optimizer:
                self._onebit_compressed = True
            else:
                # replace, don't mutate: the caller may use the same
                # instance on the compressed path
                optimizer = dataclasses.replace(optimizer,
                                                with_compression=False)
                log_dist(
                    "1-bit optimizer: compressed comm needs pure-DP ZeRO-0 "
                    "without offload — falling back to exact communication "
                    "(no compression, no error-state memory)", ranks=[0])
        self.optimizer = optimizer

        # ---- host (ZeRO-Offload/Infinity) optimizer: fp32 master + moments in
        # host RAM or on NVMe, step on CPU via the native kernel
        # (reference stage_1_and_2.py:1031 cpu-offload, stage3.py:1735 + swap)
        self._host_opt = None
        if self.offload_optimizer:
            from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer

            try:
                self._host_opt = HostOffloadOptimizer(
                    optimizer, zc.offload_optimizer, self.compute_dtype)
            except ValueError as e:
                log_dist(f"offload_optimizer: {e}; keeping device-state path",
                         ranks=[0])
        self.client_lr_scheduler = lr_scheduler
        if lr_scheduler is None and config.scheduler_name is not None:
            lr_scheduler = build_lr_scheduler(config.scheduler_name,
                                              config.scheduler_params, optimizer)
        self.lr_scheduler = lr_scheduler
        if self.lr_scheduler is not None and self.lr_scheduler.last_batch_iteration < 0:
            self.lr_scheduler.step(0)  # prime initial LR (warmup start)

        # ---- shardings
        self._build_shardings()

        # ---- state init (zero.Init analog: params born sharded on device)
        self._init_rng = init_rng if init_rng is not None else jax.random.PRNGKey(config.seed)
        self.state = self._init_state()
        self._dropout_rng = jax.random.fold_in(self._init_rng, 0x5eed)

        # ---- debug/safe mode (SURVEY §5.2: the functional design makes
        # distributed invariants checkable as placements — DSTPU_DEBUG=1)
        from deepspeed_tpu.utils.debug import (
            check_sharding_invariants, debug_mode_enabled)

        self._debug_mode = debug_mode_enabled()
        if self._debug_mode:
            for p in check_sharding_invariants(self):
                logger.warning("sharding invariant (post-init): %s", p)

        # ---- progressive layer drop (reference engine.py pld wiring)
        self.progressive_layer_drop = None
        self._use_pld = False
        if config.pld_config.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import (
                ProgressiveLayerDrop)

            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=config.pld_config.theta, gamma=config.pld_config.gamma)
            import inspect

            self._use_pld = "pld_theta" in inspect.signature(
                model.apply).parameters
            if not self._use_pld:
                log_dist("progressive_layer_drop: model.apply does not "
                         "accept pld_theta — schedule tracked but layers "
                         "are NOT dropped", ranks=[0])

        # ---- random-LTD token routing (reference data_routing wiring,
        # basic_layer.py RandomLayerTokenDrop): the scheduler's kept-token
        # count is passed to model.apply as a STATIC ``ltd_keep`` so the
        # gather->block->scatter shapes stay compile-time constants (one
        # compile per schedule granule, like the legacy curriculum).
        self.random_ltd_scheduler = None
        self._use_random_ltd = False
        if config.random_ltd_enabled:
            from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
                RandomLTDScheduler)
            import inspect

            self.random_ltd_scheduler = RandomLTDScheduler(
                config.random_ltd_params)
            self._use_random_ltd = "ltd_keep" in inspect.signature(
                model.apply).parameters
            if not self._use_random_ltd:
                log_dist("random_ltd: model.apply does not accept "
                         "ltd_keep — schedule tracked but tokens are NOT "
                         "dropped", ranks=[0])
            elif self._use_pld:
                log_dist("random_ltd and progressive_layer_drop are "
                         "mutually exclusive; disabling random_ltd",
                         ranks=[0])
                self._use_random_ltd = False
            elif self._onebit_compressed:
                log_dist("random_ltd is not supported on the 1-bit "
                         "compressed path; disabling", ranks=[0])
                self._use_random_ltd = False

        # XLA:CPU's collective rendezvous keys executions by (run_id, op_id)
        # only; on a starved host a straggler async step can join the NEXT
        # step's rendezvous and deadlock both.  The CPU (test) backend
        # therefore synchronizes every step; TPU keeps async dispatch.
        self._sync_each_step = (self.accelerator.name() == "cpu" and
                                os.environ.get("DSTPU_SYNC_EACH_STEP") != "0")

        # ---- legacy curriculum learning (engine.py:1653 curriculum_seqlen
        # injection): batches are truncated host-side to the scheduled
        # seqlen. Each DISTINCT seqlen compiles once, so the difficulty
        # step should be a multiple of a reasonable tile (reference tells
        # users the same for attention kernels).
        self.curriculum_scheduler = None
        if config.curriculum_enabled_legacy:
            from deepspeed_tpu.runtime.data_pipeline import (
                CurriculumScheduler)

            self.curriculum_scheduler = CurriculumScheduler(
                config.curriculum_params_legacy)

        # ---- counters (reference engine attrs)
        self.micro_steps = 0
        self.global_steps = 0
        self.skipped_steps = 0
        self.gas = config.gradient_accumulation_steps
        self._grad_acc = None       # accumulated grads for fwd/bwd/step API
        self._acc_count = 0
        self._global_grad_norm = None

        # ---- compiled steps
        self._compiled_train_step = None
        self._compiled_micro_grad = None
        self._compiled_apply_grads = None
        self._compiled_eval = None

        # ---- data / monitor / timers
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data, collate_fn=collate_fn)
        self.timers = SynchronizedWallClockTimer(
            # dstpu-lint: fence=timer sync_fn IS the declared wall-clock fence (utils/timer.py)
            sync_fn=lambda: jax.block_until_ready(self.state.params))
        self.tput_timer = ThroughputTimer(
            batch_size=config.train_batch_size,
            steps_per_output=config.steps_per_print or 50)
        if hasattr(model, "flops_per_token"):
            try:
                self.tput_timer.flops_per_sample = model.flops_per_token()
            except Exception:
                pass
        from deepspeed_tpu.monitor.monitor import MonitorMaster

        self.monitor = MonitorMaster(config.monitor_config)

        # ---- telemetry (ISSUE 3): in-process metrics registry + optional
        # JSONL sink. Per-step cost is a few dict ops (2% budget pinned by
        # bench.py observability_overhead); device-truth metrics (device
        # step time, MFU, grad-norm, fp16 skips, memory) are sampled at a
        # periodic block_until_ready fence so async dispatch survives.
        tcfg = config.telemetry_config
        self.telemetry = None
        self._telemetry_flops: Optional[float] = None  # None=unprobed, 0=n/a
        self._telemetry_bytes: Optional[float] = None  # cost_analysis bytes
        self._fence_t: Optional[float] = None
        self._fence_step = 0
        self._fence_tokens = 0
        self._owned_sink = None
        # span-graph tracer (ISSUE 11): step windows, sentinel-check
        # fences, rewind recovery and checkpoint save/load — all stamped
        # host-side at fences that already exist (default off)
        self.tracer = None
        self._train_trace = None
        self._spans_sink = None
        if tcfg.enabled:
            from deepspeed_tpu import telemetry as _tele

            self.telemetry = _tele.get_registry()
            if tcfg.jsonl_path and jax.process_index() == 0 \
                    and self.telemetry.sink is None:
                try:
                    self._owned_sink = _tele.JsonlSink(tcfg.jsonl_path)
                    self.telemetry.attach_sink(self._owned_sink)
                except Exception as e:
                    logger.warning(f"telemetry jsonl sink disabled: {e}")
            if tcfg.spans:
                span_sink = None
                if tcfg.spans_path and jax.process_index() == 0:
                    try:
                        self._spans_sink = _tele.JsonlSink(tcfg.spans_path)
                        span_sink = self._spans_sink
                    except Exception as e:
                        logger.warning(f"telemetry spans sink disabled: {e}")
                if span_sink is None:
                    span_sink = self.telemetry.sink  # interleave, if any
                self.tracer = _tele.SpanTracer(sink=span_sink)
                self._train_trace = self.tracer.new_trace()
        # ---- flight recorder + SLO seam (ISSUE 13): the recorder tees
        # the telemetry/span streams into bounded rings and dumps one
        # postmortem JSON when the sentinel hits an actionable anomaly;
        # an SLOEngine attached via set_slo() is evaluated at the
        # sentinel's existing check fence (no extra device syncs).
        self.flight_recorder = None
        self.slo = None
        # the sink THIS engine attached to the (global) registry — the
        # owned JsonlSink itself, or the flight-recorder tee wrapping
        # it. _shutdown compares against this, not _owned_sink: with
        # the tee in place an identity check on the bare sink would
        # never match and the registry would keep a closed sink
        self._attached_sink = self._owned_sink
        if tcfg.enabled and tcfg.flight_recorder:
            from deepspeed_tpu import telemetry as _tele

            self.flight_recorder = _tele.FlightRecorder(
                dump_dir=tcfg.flight_dir or None, registry=self.telemetry)
            self._attached_sink = self.flight_recorder.tee(
                self.telemetry.sink)
            self.telemetry.attach_sink(self._attached_sink)
            if self.tracer is not None:
                if self.tracer.sink is self._spans_sink \
                        and self._spans_sink is not None:
                    self.tracer.sink = self.flight_recorder.tee(
                        self._spans_sink)
                else:
                    # interleaved spans ride the registry sink, which is
                    # now the tee — point the tracer at the same tee so
                    # spans are recorded exactly once
                    self.tracer.sink = self.telemetry.sink
        # ---- training resilience (ISSUE 10): anomaly sentinel + finite-grad
        # guard + rewind-and-skip auto-recovery + SDC audits. The sentinel
        # consumes per-step device scalars lazily: they queue as jax arrays
        # and are fetched in ONE batch at the check fence, so detection adds
        # no per-step syncs.
        rcfg = config.resilience_config
        self.resilience_config = rcfg
        self._check_finite_grads = (rcfg.check_finite_grads
                                    if rcfg.check_finite_grads is not None
                                    else rcfg.enabled)
        self.sentinel = None
        self._pending_anomaly_reads: list = []
        self._rewind_budget = None
        self._rewinds_since_clean = 0
        self._resilience_baseline_saved = False
        self._sdc_quarantine_cb: Optional[Callable] = None
        self.sdc_suspect_devices: Tuple[int, ...] = ()
        self.rewind_log: list = []
        if rcfg.enabled:
            from deepspeed_tpu.elasticity.elastic_agent import (
                RollingWindowBudget)
            from deepspeed_tpu.runtime.sentinel import TrainingSentinel

            self.sentinel = TrainingSentinel(
                window=rcfg.window, min_history=rcfg.min_history,
                spike_zscore=rcfg.spike_zscore,
                divergence_patience=rcfg.divergence_patience,
                fp16=self.fp16_enabled)
            self._rewind_budget = RollingWindowBudget(
                rcfg.max_rewinds, rcfg.rewind_window_s)
        self._sentinel_interval = rcfg.check_interval or (
            tcfg.sync_interval if (self.telemetry is not None
                                   and tcfg.sync_interval) else 1)
        import deepspeed_tpu.comm as dist

        dist.configure(comms_config=None, enabled=config.comms_logger_config.enabled,
                       prof_all=config.comms_logger_config.prof_all,
                       prof_ops=config.comms_logger_config.prof_ops,
                       verbose=config.comms_logger_config.verbose)

        log_dist(
            f"DeepSpeedEngine: zero_stage={self.zero_stage} dtype={self.compute_dtype.__name__} "
            f"mesh={dict(zip(topology.get_axis_names(), topology.mesh_shape))} "
            f"batch triple=({config.train_batch_size},{config.train_micro_batch_size_per_gpu},"
            f"{config.gradient_accumulation_steps})", ranks=[0])

    # ------------------------------------------------------------------ specs
    def _build_shardings(self):
        mesh = self.mesh
        params_shape = jax.eval_shape(self.module.init, self._rng_placeholder())
        self._params_shape = params_shape
        self.master_specs = self.plan.master_specs(params_shape, self.logical_axes)
        self.compute_specs = self.plan.compute_specs(params_shape, self.logical_axes)
        self.grad_specs = self.plan.grad_specs(params_shape, self.logical_axes)
        mem_kind = "pinned_host" if (self.offload_optimizer and
                                     self.accelerator.name() == "tpu") else None
        self.master_shardings = self.plan.shardings(self.master_specs)
        if self._onebit_compressed:
            # error-feedback tensors are PER-DEVICE state: leading [dp] dim
            # sharded over the data axis (never replicated)
            opt_state_shape = jax.eval_shape(self._onebit_opt_init, params_shape)
            specs = self._specs_like(opt_state_shape)
            err = lambda t: jax.tree_util.tree_map(lambda _: P("data"), t)
            self.opt_specs = specs._replace(
                worker_error=err(opt_state_shape.worker_error),
                server_error=err(opt_state_shape.server_error))
            self.opt_shardings = self.plan.shardings(self.opt_specs)
        elif self._host_opt is None:
            opt_state_shape = jax.eval_shape(self.optimizer.init, params_shape)
            self.opt_specs = self._specs_like(opt_state_shape)
            self.opt_shardings = self.plan.shardings(self.opt_specs, memory_kind=mem_kind)
        else:  # optimizer state lives host-side in self._host_opt
            self.opt_specs = None
            self.opt_shardings = {}
        self._replicated = NamedSharding(mesh, P())
        self.state_shardings = TrainState(
            params=self.master_shardings,
            opt_state=self.opt_shardings,
            scaler=jax.tree_util.tree_map(lambda _: self._replicated,
                                          self.loss_scaler.init()),
            global_step=self._replicated,
        )

    def _rng_placeholder(self):
        return jax.random.PRNGKey(0)

    def _specs_like(self, tree_shape):
        """Map arbitrary state trees (optimizer moments) to master specs by
        shape-matching against params; scalars/unknown shapes replicate."""
        shape_to_spec: Dict[Tuple, P] = {}

        def record(p, spec):
            shape_to_spec.setdefault(tuple(p.shape), spec)

        jax.tree_util.tree_map(record, self._params_shape, self.master_specs,
                               is_leaf=lambda x: isinstance(x, P))

        def assign(leaf):
            s = tuple(leaf.shape)
            if s in shape_to_spec:
                return shape_to_spec[s]
            if len(s) == 0:
                return P()
            return self.plan.master_spec(s, None)

        return jax.tree_util.tree_map(assign, tree_shape)

    # ------------------------------------------------------------------- init
    def _init_state(self) -> TrainState:
        init_params = jax.jit(self.module.init, out_shardings=self.master_shardings)
        params = init_params(self._init_rng)
        self._params_treedef = jax.tree_util.tree_structure(params)
        scaler_state = self.loss_scaler.init()
        if self._host_opt is not None:
            # masters go to host; device keeps only the compute-dtype image
            self._host_opt.init(params)
            cast = jax.jit(
                lambda p: jax.tree_util.tree_map(
                    lambda x: x.astype(self.compute_dtype)
                    if x.dtype == jnp.float32 else x, p),
                out_shardings=self.master_shardings, donate_argnums=0)
            return TrainState(params=cast(params), opt_state={},
                              scaler=scaler_state,
                              global_step=jnp.zeros((), jnp.int32))
        opt_init = self._onebit_opt_init if self._onebit_compressed \
            else self.optimizer.init
        # dstpu-lint: disable=recompile-hazard -- one-shot optimizer-state init at engine construction
        opt_state = jax.jit(opt_init, out_shardings=self.opt_shardings)(params)
        return TrainState(params=params, opt_state=opt_state, scaler=scaler_state,
                          global_step=jnp.zeros((), jnp.int32))

    def _onebit_opt_init(self, params):
        """Optimizer state for the compressed 1-bit path: worker/server
        error carriers get a leading [dp] device dim (per-device distinct,
        sharded over the data axis)."""
        base = self.optimizer.init(params)
        dp = self.topology.data_parallel_size
        stack = lambda t: jax.tree_util.tree_map(
            lambda a: jnp.zeros((dp,) + a.shape, a.dtype), t)
        return base._replace(worker_error=stack(base.worker_error),
                             server_error=stack(base.server_error))

    # ---------------------------------------------------------- micro helpers
    def _cast_for_compute(self, params):
        specs = self.compute_specs

        def cast(p, spec):
            c = p.astype(self.compute_dtype) if p.dtype == jnp.float32 else p
            return jax.lax.with_sharding_constraint(c, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map(cast, params, specs)

    def _micro_loss_and_grads(self, params, batch, scale, rng, pld_theta=None,
                              constrain=True, ltd_keep=None):
        """Single microbatch loss+grads in compute dtype; grads carry the
        stage-dependent sharding constraint (→ reduce-scatter from stage 2).
        ``constrain=False`` drops the NamedSharding constraints for callers
        already inside a shard_map manual context (the 1-bit path)."""
        kwargs = {"pld_theta": pld_theta} if pld_theta is not None else {}
        if ltd_keep is not None:
            kwargs["ltd_keep"] = ltd_keep

        def loss_fn(master_params):
            cparams = self._cast_for_compute(master_params) if constrain else \
                jax.tree_util.tree_map(
                    lambda x: x.astype(self.compute_dtype)
                    if x.dtype == jnp.float32 else x, master_params)
            loss, metrics = self.module.apply(cparams, batch,
                                              rngs={"dropout": rng},
                                              train=True, **kwargs)
            return loss * scale, metrics

        (scaled_loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # grads accumulate in grad_accum_dtype (reference data_types.
        # grad_accum_dtype): bf16 halves the accumulation buffer
        acc_dt = jnp.bfloat16 if self.config.grad_accum_dtype == "bf16" \
            else jnp.float32
        if constrain:
            grads = jax.tree_util.tree_map(
                lambda g, s: jax.lax.with_sharding_constraint(
                    g.astype(acc_dt), NamedSharding(self.mesh, s)),
                grads, self.grad_specs)
        else:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(acc_dt), grads)
        return scaled_loss, grads, metrics

    def _apply_grads(self, state: TrainState, grads, lr):
        """unscale → overflow check → clip → optimizer → scale update.
        (_take_model_step analog, engine.py:1886)."""
        inv = 1.0 / state.scaler.cur_scale
        grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
        if self.fp16_enabled or self._check_finite_grads:
            # fp16: dynamic-loss-scale overflow. bf16/fp32 with the
            # finite-grad guard (ISSUE 10 satellite): a nonfinite grad —
            # poisoned batch, numeric blow-up — must not step into the
            # params; same skip-and-count semantics as the fp16 path
            # (global_step below advances only on applied updates).
            overflow = has_inf_or_nan(grads)
        else:
            overflow = jnp.zeros((), bool)
        norm = global_grad_norm(grads)
        if self.config.gradient_clipping > 0:
            grads, norm = clip_grads_by_global_norm(grads, self.config.gradient_clipping, norm)
        new_params, new_opt = self.optimizer.step(state.params, grads, state.opt_state, lr)
        # skip the update on overflow (dynamic loss scaling semantics)
        new_params = jax.tree_util.tree_map(
            lambda old, new: jnp.where(overflow, old, new), state.params, new_params)
        new_opt = jax.tree_util.tree_map(
            lambda old, new: jnp.where(overflow, old, new), state.opt_state, new_opt)
        new_scaler = self.loss_scaler.update(state.scaler, overflow)
        new_state = TrainState(params=new_params, opt_state=new_opt, scaler=new_scaler,
                               global_step=state.global_step + 1 - overflow.astype(jnp.int32))
        return new_state, overflow, norm

    # ---------------------------------------------------- shared step pieces
    def _scan_micro_grads(self, state: TrainState, batch, rng, pld_theta=None,
                          constrain=True, rng_fold=None, ltd_keep=None):
        """Grad-accumulation scan over the gas microbatches (shared by the
        fused device step, the host-offload grad step and the 1-bit
        shard_map step). ``rng_fold(rng, i)`` customizes the per-microbatch
        rng derivation (the 1-bit path folds in the device index)."""
        scale = state.scaler.cur_scale
        rng_fold = rng_fold or jax.random.fold_in

        def micro(carry, mb_and_i):
            grads_acc, loss_acc = carry
            mb, i = mb_and_i
            sub = rng_fold(rng, i)
            _, grads, metrics = self._micro_loss_and_grads(
                state.params, mb, scale, sub, pld_theta, constrain=constrain,
                ltd_keep=ltd_keep)
            grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
            return (grads_acc, loss_acc + metrics["loss"]), None

        acc_dt = jnp.bfloat16 if self.config.grad_accum_dtype == "bf16" \
            else jnp.float32
        if constrain:
            grads0 = jax.tree_util.tree_map(
                lambda p, s: jax.lax.with_sharding_constraint(
                    jnp.zeros(p.shape, acc_dt), NamedSharding(self.mesh, s)),
                state.params, self.grad_specs)
        else:
            grads0 = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, acc_dt), state.params)
        (grads, loss_sum), _ = jax.lax.scan(
            micro, (grads0, jnp.zeros((), jnp.float32)),
            (batch, jnp.arange(self.gas)))
        return grads, loss_sum

    def _unscale_epilogue(self, grads, scaler):
        """gas-mean + loss-scale unscale + overflow/norm (shared epilogue of
        both host-step entry points)."""
        inv = 1.0 / (self.gas * scaler.cur_scale)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)
        overflow = has_inf_or_nan(grads) \
            if (self.fp16_enabled or self._check_finite_grads) \
            else jnp.zeros((), bool)
        return grads, overflow, global_grad_norm(grads)

    # ---------------------------------------------------- host (offload) step
    def _build_grad_step(self):
        """Compiled grad-accumulation-only step for the host-optimizer path:
        returns mean unscaled grads + metrics; the optimizer update happens
        on the CPU (ZeRO-Offload semantics)."""

        def grad_step(state: TrainState, batch, rng, ltd_keep=None):
            grads, loss_sum = self._scan_micro_grads(state, batch, rng,
                                                     ltd_keep=ltd_keep)
            grads, overflow, norm = self._unscale_epilogue(grads, state.scaler)
            # host optimizer consumes grads in the MASTER layout: each
            # process updates exactly the master shards it owns (multi-host
            # offload partitioning; single-host this is a no-op reshard)
            grads = jax.tree_util.tree_map(
                lambda g, s: jax.lax.with_sharding_constraint(
                    g, NamedSharding(self.mesh, s)), grads, self.master_specs)
            metrics = {"loss": loss_sum / self.gas, "overflow": overflow,
                       "grad_norm": norm, "loss_scale": state.scaler.cur_scale}
            return grads, metrics

        # ltd_keep static: shapes depend on it (same contract as the
        # fused train step)
        self._compiled_grad_step = jax.jit(grad_step, static_argnums=(3,))
        return self._compiled_grad_step

    def _host_apply(self, grads, overflow: bool, norm: float, lr):
        """CPU optimizer update on host masters; push compute-dtype params
        back (reference cpu-offload step: grads→CPU, Adam, params→device)."""
        new_scaler = jax.device_put(
            self.loss_scaler.update(self.state.scaler, jnp.asarray(overflow)),
            jax.tree_util.tree_map(lambda _: self._replicated, self.state.scaler))
        if overflow:
            self.skipped_steps += 1
            self.state = self.state._replace(scaler=new_scaler)
            return
        clip = self.config.gradient_clipping
        factor = min(1.0, clip / (norm + 1e-6)) if clip and clip > 0 else 1.0
        # align grads to the MASTER layout (no-op when already aligned; the
        # fused grad_step constrains in-program, but the manual
        # forward/backward/step path reaches here with grad-spec placement)
        grads = jax.device_put(grads, self.master_shardings)
        grads_host = self._host_opt.grads_to_host(grads)
        out = self._host_opt.step(grads_host, lr=float(np.asarray(lr)),
                                  grad_scale=factor)
        new_params = self._host_opt.images_to_device(
            out, self._params_treedef, self.master_shardings)
        self.state = TrainState(
            params=new_params, opt_state={}, scaler=new_scaler,
            global_step=self.state.global_step + 1)

    # -------------------------------------------------------- fused train step
    def _build_train_step(self, batch=None):
        if self._onebit_compressed:
            return self._build_onebit_train_step(batch)
        gas = self.gas

        def train_step(state: TrainState, batch, lr, rng, pld_theta=None,
                       ltd_keep=None):
            grads, loss_sum = self._scan_micro_grads(state, batch, rng,
                                                     pld_theta,
                                                     ltd_keep=ltd_keep)
            # back to f32 for unscale/clip/optimizer regardless of the
            # accumulation dtype
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / gas, grads)
            new_state, overflow, norm = self._apply_grads(state, grads, lr)
            metrics = {"loss": loss_sum / gas, "overflow": overflow, "grad_norm": norm,
                       "loss_scale": state.scaler.cur_scale}
            return new_state, metrics

        batch_sharding_fn = self._gas_batch_shardings
        # ltd_keep is STATIC (it sets gather/scatter shapes): one compile
        # per schedule granule, bounded by the scheduler's seq_per_step
        self._compiled_train_step = jax.jit(train_step, donate_argnums=(0,),
                                            static_argnums=(5,))
        # subclass step builders (pipeline engine) and the 1-bit path keep
        # the 4-arg signature; _run_fused_step checks this flag
        self._step_takes_extra_args = True
        return self._compiled_train_step

    def _build_onebit_train_step(self, batch):
        """Compressed-comm train step (reference: engine disables backward
        allreduce for 1-bit optimizers and lets compressed_allreduce carry
        the sync — runtime/comm/nccl.py:54). shard_map over the data axis
        keeps grads LOCAL; the optimizer's error-compensated momentum sync
        is the only cross-device traffic (int8 signs over ICI)."""
        from deepspeed_tpu.utils.jax_compat import shard_map

        if self._use_pld:
            log_dist("progressive_layer_drop is not supported on the 1-bit "
                     "compressed path; disabling", ranks=[0])
            self._use_pld = False
        if self.config.gradient_clipping and self.config.gradient_clipping > 0:
            # the global grad norm is undefined when grads never leave the
            # device (only the momentum is synced) — same limitation as the
            # reference's 1-bit optimizers; grad_norm stays a diagnostic
            # (norm of the concatenated local grads)
            log_dist("gradient_clipping is not supported with compressed "
                     "1-bit communication; ignoring (reference 1-bit Adam "
                     "has the same limitation)", ranks=[0])

        mesh, gas, opt = self.mesh, self.gas, self.optimizer
        fp16 = self.fp16_enabled
        loss_scaler = self.loss_scaler

        rep = lambda t: jax.tree_util.tree_map(lambda _: P(), t)
        err_specs = jax.tree_util.tree_map(
            lambda _: P("data"), self.state.opt_state.worker_error)
        state_specs = TrainState(
            params=rep(self.state.params),
            opt_state=rep(self.state.opt_state)._replace(
                worker_error=err_specs, server_error=err_specs),
            scaler=rep(self.state.scaler),
            global_step=P())
        batch_specs = jax.tree_util.tree_map(
            lambda x: P(None, *self.plan.batch_spec(x.ndim - 1)), batch)
        metric_specs = {"loss": P(), "overflow": P(), "grad_norm": P(),
                        "loss_scale": P()}

        def step(state: TrainState, batch, lr, rng):
            params = state.params
            drop0 = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            add0 = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
            my = jax.lax.axis_index("data")

            grads, loss_sum = self._scan_micro_grads(
                state, batch, rng, constrain=False,
                rng_fold=lambda r, i: jax.random.fold_in(
                    jax.random.fold_in(r, i), my))
            grads, overflow, _ = self._unscale_epilogue(grads, state.scaler)
            if fp16:
                overflow = jax.lax.psum(
                    overflow.astype(jnp.int32), "data") > 0
            # diagnostic only — NOT used for clipping (see builder note):
            # norm of the concatenated per-device local grads
            # (fp16/fused_optimizer get_grad_norm over local groups)
            sumsq = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                        for g in jax.tree_util.tree_leaves(grads))
            norm = jnp.sqrt(jax.lax.psum(sumsq, "data"))
            inner = state.opt_state._replace(
                worker_error=drop0(state.opt_state.worker_error),
                server_error=drop0(state.opt_state.server_error))
            new_p, new_opt = opt.step(params, grads, inner, lr,
                                      axis_name="data")
            skip = lambda old, new: jax.tree_util.tree_map(
                lambda o, n: jnp.where(overflow, o, n), old, new)
            new_p = skip(params, new_p)
            new_opt = skip(inner, new_opt)
            new_state = TrainState(
                params=new_p,
                opt_state=new_opt._replace(
                    worker_error=add0(new_opt.worker_error),
                    server_error=add0(new_opt.server_error)),
                scaler=loss_scaler.update(state.scaler, overflow),
                global_step=state.global_step + 1 - overflow.astype(jnp.int32))
            metrics = {"loss": jax.lax.pmean(loss_sum / gas, "data"),
                       "overflow": overflow, "grad_norm": norm,
                       "loss_scale": state.scaler.cur_scale}
            return new_state, metrics

        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(state_specs, batch_specs, P(), P()),
            out_specs=(state_specs, metric_specs),
            # params/moments stay consensus by construction (compressed sync
            # ends in an allgather reconstruction identical on every device)
            # — vma typing cannot prove that statically
            check_vma=False)
        self._compiled_train_step = jax.jit(sharded, donate_argnums=(0,))
        return self._compiled_train_step

    def _gas_batch_shardings(self, batch):
        def shard(x):
            spec = self.plan.batch_spec(x.ndim - 1)
            return NamedSharding(self.mesh, P(None, *spec))
        return jax.tree_util.tree_map(shard, batch)

    def _batch_shardings(self, batch):
        return jax.tree_util.tree_map(
            lambda x: NamedSharding(self.mesh, self.plan.batch_spec(x.ndim)), batch)

    # --------------------------------------------------------------- user API
    def _ensure_train_iter(self):
        """Engine-owned repeating iterator over ``training_dataloader``
        (rebuilt after a checkpoint load / anomaly rewind invalidates it)."""
        assert self.training_dataloader is not None, \
            "train_batch needs a data_iter or training_data at init"
        if not hasattr(self, "_train_iter") or self._train_iter is None:
            from deepspeed_tpu.runtime.dataloader import RepeatingLoader

            self._train_iter = iter(RepeatingLoader(self.training_dataloader))
        return self._train_iter

    def train_batch(self, data_iter: Optional[Iterator] = None):
        """Pull ``gas`` microbatches, run ONE fused compiled step.
        Microbatch leaves are stacked on a leading [gas] dim."""
        # anomaly rewind can only fast-forward a stream the ENGINE owns;
        # track which source fed the step so recovery never rewinds the
        # engine loader while a caller-supplied iterator keeps advancing
        self._engine_owned_stream = data_iter is None
        if data_iter is None:
            # baseline checkpoint BEFORE the first pull: the rewind target
            # of an anomaly in the first interval must pair step-0 params
            # with dataloader offset 0, or the resumed stream desyncs
            if (self.sentinel is not None
                    and self.resilience_config.checkpoint_dir
                    and not self._resilience_baseline_saved):
                self._resilience_baseline_saved = True
                self.save_checkpoint(self.resilience_config.checkpoint_dir)
            data_iter = self._ensure_train_iter()
        micro_batches = [next(data_iter) for _ in range(self.gas)]
        batch = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *micro_batches)
        return self._run_fused_step(batch)

    def train_batch_from_stacked(self, batch):
        """As train_batch, but the caller supplies the [gas, ...] stacked batch."""
        self._engine_owned_stream = False  # caller owns the data stream
        return self._run_fused_step(batch)

    def _run_fused_step(self, batch):
        h = getattr(self, "_preemption_handler", None)
        if h is not None:
            h.poll()  # deferred preemption: final save at the step boundary
        if self._host_opt is not None:
            return self._run_host_step(batch)
        if self._compiled_train_step is None:
            self._build_train_step(batch)
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        t_start = time.perf_counter()
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        rng = jax.random.fold_in(self._dropout_rng, self.global_steps)
        batch = self._apply_curriculum(batch)
        batch = jax.device_put(batch, self._gas_batch_shardings(batch))
        ltd_keep = None
        if self._use_random_ltd:
            seq_len = int(batch["input_ids"].shape[-1]) \
                if isinstance(batch, dict) and "input_ids" in batch else None
            keep = self.random_ltd_scheduler.update_seq(self.global_steps)
            if seq_len is None or keep < seq_len:
                ltd_keep = keep
        with jax.profiler.TraceAnnotation("dstpu/train_step"):
            if self._use_pld:
                theta = jnp.asarray(self.progressive_layer_drop.get_theta(),
                                    jnp.float32)
                self.state, metrics = self._compiled_train_step(
                    self.state, batch, lr, rng, theta)
            elif not getattr(self, "_step_takes_extra_args", False):
                # 1-bit shard_map step and subclass (pipeline) step builders
                # keep the 4-arg signature
                if ltd_keep is not None and not getattr(self, "_ltd_warned",
                                                        False):
                    log_dist("random_ltd: this engine's train step does not "
                             "route tokens — schedule tracked but NOT applied",
                             ranks=[0])
                    self._ltd_warned = True
                self.state, metrics = self._compiled_train_step(
                    self.state, batch, lr, rng)
            else:
                self.state, metrics = self._compiled_train_step(
                    self.state, batch, lr, rng, None, ltd_keep)
        self._global_grad_norm = metrics["grad_norm"]
        self.micro_steps += self.gas
        self.global_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._after_step(metrics)
        self.timers(TRAIN_BATCH_TIMER).stop(record=True)
        self.tput_timer.stop(global_step=True)
        if self.telemetry is not None:
            self._record_step_telemetry(
                metrics, batch, time.perf_counter() - t_start,
                ltd_keep=ltd_keep)
        if self.sentinel is not None:
            self._resilience_step(metrics, batch)
        if self._sync_each_step:
            # dstpu-lint: fence=opt-in per-step fence (config sync_each_step)
            jax.block_until_ready(self.state.params)
        return metrics["loss"]

    def _run_host_step(self, batch):
        if getattr(self, "_compiled_grad_step", None) is None:
            self._build_grad_step()
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        t_start = time.perf_counter()
        lr = self.get_lr()[0]
        rng = jax.random.fold_in(self._dropout_rng, self.global_steps)
        batch = self._apply_curriculum(batch)
        batch = jax.device_put(batch, self._gas_batch_shardings(batch))
        ltd_keep = None
        if self._use_random_ltd:
            seq_len = int(batch["input_ids"].shape[-1]) \
                if isinstance(batch, dict) and "input_ids" in batch else None
            keep = self.random_ltd_scheduler.update_seq(self.global_steps)
            if seq_len is None or keep < seq_len:
                ltd_keep = keep
        grads, metrics = self._compiled_grad_step(self.state, batch, rng,
                                                  ltd_keep)
        overflow = bool(jax.device_get(metrics["overflow"]))  # dstpu-lint: fence=host-optimizer path: overflow/norm gate the host apply
        norm = float(jax.device_get(metrics["grad_norm"]))  # dstpu-lint: fence=host-optimizer path: overflow/norm gate the host apply
        self._host_apply(grads, overflow, norm, lr)
        self._global_grad_norm = metrics["grad_norm"]
        self.micro_steps += self.gas
        self.global_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._after_step(metrics)
        self.timers(TRAIN_BATCH_TIMER).stop(record=True)
        self.tput_timer.stop(global_step=True)
        if self.telemetry is not None:
            # host-optimizer path: the update already synchronized on the
            # grads, so wall time here IS device time
            self._record_step_telemetry(
                metrics, batch, time.perf_counter() - t_start)
        if self.sentinel is not None:
            self._resilience_step(metrics, batch)
        if self._sync_each_step:
            # dstpu-lint: fence=opt-in per-step fence (config sync_each_step)
            jax.block_until_ready(self.state.params)
        return metrics["loss"]

    def _apply_curriculum(self, batch):
        """Legacy curriculum: truncate sequences to the scheduled difficulty
        (reference engine.py:1653-1656 curriculum_seqlen). Host-side slicing
        — each distinct seqlen is one compile."""
        if self.curriculum_scheduler is None:
            return batch
        seqlen = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)
        seq_keys = {"input_ids", "labels", "attention_mask",
                    "token_type_ids", "position_ids"}

        def trunc(node):
            if isinstance(node, dict):
                return {k: (v[..., :seqlen]
                            if k in seq_keys and hasattr(v, "ndim") and
                            v.ndim >= 2 else trunc(v))
                        for k, v in node.items()}
            return node

        return trunc(batch)

    def _after_step(self, metrics):
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        self._after_step_impl(metrics)

    def _after_step_impl(self, metrics):
        cfg = self.config
        if self._debug_mode and cfg.steps_per_print and \
                self.global_steps % cfg.steps_per_print == 0:
            from deepspeed_tpu.utils.debug import check_sharding_invariants

            for p in check_sharding_invariants(self):
                logger.warning("sharding invariant (step %d): %s",
                               self.global_steps, p)
        # autotuning experiment: report throughput after warmup then exit
        # (reference exits inside engine.forward:1687-1691 once profiled)
        result_path = os.environ.get("DSTPU_AUTOTUNING_RESULT")
        if result_path:
            # fence EVERY armed step before tput_timer.stop(): under async
            # dispatch the timer otherwise brackets only the dispatch and
            # self-reports physically impossible rates (36M tokens/sec
            # observed on the tunnel chip in round 4)
            float(jax.device_get(metrics["loss"]))  # dstpu-lint: fence=autotune armed-step fence: honest rates
        if result_path and self.global_steps >= 5:
            import json as _json

            samples_per_sec = self.tput_timer.avg_samples_per_sec() or 0.0
            with open(result_path, "w") as f:
                _json.dump({"metric": samples_per_sec,
                            "unit": "samples/sec"}, f)
            log_dist(f"autotuning: wrote metric {samples_per_sec:.2f} "
                     f"samples/sec, exiting", ranks=[0])
            raise SystemExit(0)
        if self.fp16_enabled:
            # host round-trip only when someone asks; keep async by default
            pass
        # monitor cadence decoupled from print cadence (monitor_interval
        # config key; 0 = legacy coupling to steps_per_print)
        mon_interval = cfg.monitor_interval or max(cfg.steps_per_print or 0, 1)
        if self.monitor.enabled and self.global_steps % mon_interval == 0:
            # dstpu-lint: fence=monitor cadence read (mon_interval-gated)
            loss = float(jax.device_get(metrics["loss"]))
            events = [("Train/Samples/train_loss", loss, self.global_steps),
                      ("Train/Samples/lr", self.get_lr()[0], self.global_steps)]
            if self.fp16_enabled:
                events.append(("Train/Samples/loss_scale",
                               float(jax.device_get(metrics["loss_scale"])), self.global_steps))  # dstpu-lint: fence=monitor cadence read
            self.monitor.write_events(events)
        if cfg.steps_per_print and self.global_steps % cfg.steps_per_print == 0:
            # dstpu-lint: fence=steps_per_print cadence read
            loss = float(jax.device_get(metrics["loss"]))
            log_dist(f"step={self.global_steps} loss={loss:.4f} lr={self.get_lr()[0]:.3e}",
                     ranks=[0])
            if cfg.wall_clock_breakdown:
                self.timers.log([TRAIN_BATCH_TIMER, FORWARD_GLOBAL_TIMER,
                                 BACKWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER],
                                memory_breakdown=cfg.memory_breakdown)

    # -------------------------------------------------------------- telemetry
    @staticmethod
    def _batch_token_count(batch) -> int:
        """Tokens in one engine step (LM batches); sample count otherwise."""
        if isinstance(batch, dict) and "input_ids" in batch:
            try:
                return int(np.prod(np.shape(batch["input_ids"])))
            except Exception:
                pass
        return 0

    def _record_step_telemetry(self, metrics, batch, wall_dt: float,
                               ltd_keep=None):
        """Hot-path accounting: a histogram observe + two counter incs per
        step. Everything that would force a device sync (grad-norm, fp16
        skips, memory, device-time MFU) waits for the periodic fence."""
        reg = self.telemetry
        tokens = self._batch_token_count(batch)
        reg.counter("train/steps").inc()
        if tokens:
            self._fence_tokens += tokens
            reg.counter("train/tokens").inc(tokens)
        # dispatch-bounded under async dispatch (TPU); device truth comes
        # from the fence-to-fence gauge below
        reg.histogram("train/step_wall_ms").observe(wall_dt * 1e3)
        interval = self.config.telemetry_config.sync_interval
        if interval and (self.global_steps % interval == 0
                         or self.global_steps == 1):
            self._telemetry_fence(metrics, batch, ltd_keep)

    def _reset_telemetry_window(self):
        """Invalidate the fence-to-fence device-rate baseline. Called
        around work that is NOT training steps (checkpoint save/load) so
        a multi-second blocking save between fences is never charged to
        train/device_step_time_ms or train/mfu."""
        self._fence_t = None
        self._fence_step = self.global_steps
        self._fence_tokens = 0

    def _telemetry_fence(self, metrics, batch, ltd_keep=None):
        """Periodic block_until_ready fence: honest device-time step
        latency + MFU from fence-to-fence elapsed, plus the scalars whose
        read would otherwise break async dispatch. Assumes fence-to-fence
        wall time is training; engine-visible non-training work
        (checkpoint save/load) resets the window via
        _reset_telemetry_window — caller-side stalls between steps are
        still charged (they are invisible from here)."""
        reg = self.telemetry
        # dstpu-lint: fence=THE periodic telemetry fence (sync_interval): device-truth metrics
        jax.block_until_ready(self.state.params)
        now = time.perf_counter()
        steps = self.global_steps - self._fence_step
        if self._fence_t is not None and steps > 0:
            if self.tracer is not None:
                # fence-to-fence window as one span: both instants were
                # observed at fences the untraced engine already paid
                self.tracer.record(
                    "step_window", self._fence_t, now,
                    trace_id=self._train_trace, steps=steps,
                    tokens=self._fence_tokens,
                    end_step=self.global_steps)
            dev_step_s = (now - self._fence_t) / steps
            reg.gauge("train/device_step_time_ms").set(dev_step_s * 1e3)
            if self._fence_tokens:
                reg.gauge("train/tokens_per_sec").set(
                    self._fence_tokens / (now - self._fence_t))
            flops = self._telemetry_flops  # probed at the previous fence
            if flops:
                reg.gauge("train/model_tflops").set(flops / dev_step_s / 1e12)
                from deepspeed_tpu.telemetry.mfu import mfu as _mfu

                u = _mfu(flops, dev_step_s)
                if u is not None:
                    reg.gauge("train/mfu").set(u)
        # probe flops AFTER reading the window so the probe's one-time
        # lower+compile never pollutes a device-rate sample; the first
        # fence is step 1, so the compile lands in warmup
        self._train_step_flops(batch, ltd_keep)
        self._fence_step = self.global_steps
        self._fence_tokens = 0
        # device-truth scalars: the fence already drained the pipeline, so
        # these fetches are free of extra sync
        try:
            reg.gauge("train/grad_norm").set(
                float(jax.device_get(metrics["grad_norm"])))  # dstpu-lint: fence=post-fence read: pipeline already drained
            reg.gauge("train/loss").set(
                float(jax.device_get(metrics["loss"])))  # dstpu-lint: fence=post-fence read: pipeline already drained
            if self.fp16_enabled:
                reg.gauge("train/loss_scale").set(
                    float(jax.device_get(metrics["loss_scale"])))  # dstpu-lint: fence=post-fence read: pipeline already drained
                # device global_step counts only successful steps; the host
                # counter counts all — the difference IS the skip count
                device_gs = int(jax.device_get(self.state.global_step))  # dstpu-lint: fence=post-fence read: pipeline already drained
                reg.gauge("train/fp16_skipped_steps").set(
                    max(self.global_steps - device_gs, 0))
            elif self._check_finite_grads:
                # same accounting for the bf16/fp32 finite-grad guard
                device_gs = int(jax.device_get(self.state.global_step))  # dstpu-lint: fence=post-fence read: pipeline already drained
                reg.gauge("train/nonfinite_skipped_steps").set(
                    max(self.global_steps - device_gs, 0))
        except Exception:
            pass
        stats = self.accelerator.memory_stats()
        if stats:
            reg.gauge("device/mem_in_use_bytes").set(
                stats.get("bytes_in_use", 0))
            reg.gauge("device/mem_peak_bytes").set(
                stats.get("peak_bytes_in_use", 0))
        reg.flush(step=self.global_steps)
        # window baseline AFTER the probe + fetches, so only training
        # steps are charged to the next fence-to-fence device rate
        self._fence_t = time.perf_counter()

    def _train_step_flops(self, batch, ltd_keep=None) -> Optional[float]:
        """Model flops of ONE fused train step, cached after first probe.
        Primary: XLA's own cost_analysis of the compiled step (post-fusion,
        includes remat recompute — the PaLM MFU numerator). Costs one extra
        lower+compile at the first fence (disable via
        telemetry.cost_analysis). Fallback: analytic 6*N*tokens."""
        if self._telemetry_flops is not None:
            return self._telemetry_flops or None
        flops = 0.0
        # the probe costs one extra lower+compile of the train step, so it
        # runs only where the result is actually consumed: a JSONL sink is
        # attached, or the accelerator has a peak entry (MFU computable —
        # real TPU, or DSTPU_PEAK_TFLOPS set). CPU unit tests take the
        # free analytic fallback.
        worth_probing = (self.telemetry.sink is not None
                         or self.accelerator.peak_tflops() is not None)
        if (self.config.telemetry_config.cost_analysis and worth_probing
                and self._compiled_train_step is not None
                and getattr(self, "_step_takes_extra_args", False)
                and not self._use_pld):
            try:
                lowered = self._compiled_train_step.lower(
                    self.state, batch,
                    jnp.zeros((), jnp.float32),
                    jax.random.PRNGKey(0), None, ltd_keep)
                ca = lowered.compile().cost_analysis()
                if isinstance(ca, list):
                    ca = ca[0] if ca else {}
                flops = float((ca or {}).get("flops", 0.0) or 0.0)
                # cost_analysis sees the PER-DEVICE partitioned module;
                # scale to global so both flops sources and the aggregate
                # peak denominator (mfu.peak_flops_per_sec over all chips)
                # agree. Replicated compute makes this a slight
                # overcount — acceptable for an MFU estimate.
                flops *= jax.device_count()
                # bytes accessed ride the same probe — the memory axis
                # of the train step's roofline row (ISSUE 11)
                self._telemetry_bytes = float(
                    (ca or {}).get("bytes accessed", 0.0)
                    or 0.0) * jax.device_count()
            except Exception as e:
                logger.warning("telemetry: cost_analysis of the train step "
                               "failed (%s: %s); using analytic flops",
                               type(e).__name__, e)
        if not flops:
            tokens = self._batch_token_count(batch)
            if tokens:
                n_params = sum(int(np.prod(l.shape)) for l in
                               jax.tree_util.tree_leaves(self._params_shape))
                flops = 6.0 * n_params * tokens
        self._telemetry_flops = flops
        return flops or None

    def train_step_attribution(self) -> dict:
        """Roofline row for the fused train step (ISSUE 11): XLA
        cost-analysis flops/bytes (probed at the telemetry fence; the
        analytic-flops fallback leaves the memory axis empty) joined
        with the fence-measured device step time and the accelerator's
        compute/bandwidth roofs. When a telemetry sink is attached, the
        row is also streamed as an ``{"kind": "attribution", "scope":
        "train"}`` record for scripts/telemetry_report.py."""
        from deepspeed_tpu.telemetry.attribution import (accelerator_peaks,
                                                         roofline_row)

        flops = self._telemetry_flops
        if not flops:
            return {}
        wall_s = None
        if self.telemetry is not None:
            ms = self.telemetry.gauge("train/device_step_time_ms").value
            if ms:
                wall_s = ms / 1e3
        peak_flops, peak_bw = accelerator_peaks()
        # _telemetry_flops/_telemetry_bytes are CLUSTER totals (the MFU
        # probe scales cost_analysis by device_count; the analytic
        # fallback counts global-batch tokens) while the accelerator
        # roofs are PER CHIP — normalize to per-chip so achieved vs
        # attainable compares like with like on multi-chip meshes
        n_dev = max(jax.device_count(), 1)
        row = roofline_row(flops / n_dev,
                           (self._telemetry_bytes or 0.0) / n_dev,
                           wall_s=wall_s, calls=self.global_steps,
                           peak_flops=peak_flops,
                           peak_bytes_per_sec=peak_bw)
        table = {"train_step": row}
        if self.telemetry is not None and self.telemetry.sink is not None:
            try:
                self.telemetry.sink.write({
                    "kind": "attribution", "scope": "train",
                    "programs": table})
            except Exception:
                pass
        return table

    # ------------------------------------------------- resilience (ISSUE 10)
    def _resilience_step(self, metrics, batch):
        """Per-step sentinel bookkeeping. The scalars queue as device
        arrays; classification happens at the check fence (one batched
        device_get — free right after a telemetry fence, which shares the
        cadence by default). Auto-checkpoints are screened: the sentinel
        drains BEFORE a save so a detected-late anomaly can never be
        published as a rewind target."""
        rcfg = self.resilience_config
        self._pending_anomaly_reads.append(
            (self.global_steps, metrics.get("loss"),
             metrics.get("grad_norm"), metrics.get("overflow")))
        save_due = (rcfg.checkpoint_dir is not None and rcfg.checkpoint_interval
                    and self.global_steps % rcfg.checkpoint_interval == 0)
        # an SDC-armed run audits BEFORE every save too: a bit flipped
        # between audits must never be published into a rewind target,
        # where the recovery reload would re-replicate it to every device
        # and the corruption would pass all future audits
        audit_due = bool(rcfg.sdc_audit_interval) and (
            save_due or self.global_steps % rcfg.sdc_audit_interval == 0)
        replay_due = (rcfg.step_replay_interval
                      and self.global_steps % rcfg.step_replay_interval == 0)
        if not (save_due or audit_due or replay_due
                or self.global_steps % self._sentinel_interval == 0):
            return
        anomaly = self._sentinel_drain()
        if anomaly is None and audit_due:
            anomaly = self._sdc_audit_check()
        if anomaly is None and replay_due:
            anomaly = self._sdc_step_replay_check(batch)
        if self.slo is not None:
            # SLO judgment at the sentinel's existing fence (ISSUE 13):
            # the training SLIs (MFU floor, anomaly rate) read gauges/
            # counters the fence just refreshed — host-only, on the SLO
            # engine's own clock
            self.slo.maybe_evaluate()
        if anomaly is not None:
            self._recover_or_raise(anomaly)
            return
        # de-escalate the skip width only once training has cleanly passed
        # the last anomaly's region — a clean check while still replaying
        # toward it must not shrink the next escalation
        if self.global_steps > getattr(self, "_last_anomaly_step", -1):
            self._rewinds_since_clean = 0
        if save_due:
            self.save_checkpoint(rcfg.checkpoint_dir)

    def _sentinel_drain(self):
        """Classify every queued step; returns the first *actionable*
        anomaly (overflows are counted but the loss scaler already handled
        them). Entries after an actionable anomaly are dropped — they ran
        on suspect params and the rewind re-executes them anyway."""
        from deepspeed_tpu.runtime.sentinel import AnomalyClass

        if not self._pending_anomaly_reads:
            return None
        t0 = time.perf_counter() if self.tracer is not None else 0.0
        pending, self._pending_anomaly_reads = \
            self._pending_anomaly_reads, []
        # dstpu-lint: fence=sentinel drain: ONE batched fetch at the declared cadence
        vals = jax.device_get([(l, n, o) for _, l, n, o in pending])
        reg = self.telemetry
        found = None
        for (step, *_), (loss, norm, ovf) in zip(pending, vals):
            a = self.sentinel.observe(
                step,
                float(loss) if loss is not None else 0.0,
                float(norm) if norm is not None else 0.0,
                bool(ovf) if ovf is not None else False)
            if a is None:
                continue
            if reg is not None:
                reg.counter(f"resilience/anomalies_{a.cls}").inc()
            if a.cls != AnomalyClass.OVERFLOW:
                found = a
                break
        if self.tracer is not None:
            # the batched fetch above is the sentinel's existing fence —
            # the span just names it
            self.tracer.record(
                "sentinel_check", t0, time.perf_counter(),
                trace_id=self._train_trace, observations=len(pending),
                step=self.global_steps,
                anomaly=(found.cls if found is not None else None))
        return found

    def _sdc_audit_check(self):
        """Cross-data-parallel-replica checksum agreement over params +
        optimizer state (replicas are bit-identical by construction; see
        sentinel.sdc_audit). A mismatch quarantines the suspect device —
        counted, evented, and surfaced to the elastic agent via
        ``set_sdc_quarantine_callback`` — and returns an SDC anomaly so
        recovery rewinds (the reload re-replicates clean bytes)."""
        from deepspeed_tpu import telemetry as _tele
        from deepspeed_tpu.runtime.sentinel import (
            AnomalyClass, TrainingAnomaly, sdc_audit)

        res = sdc_audit({"params": self.state.params,
                         "opt_state": self.state.opt_state})
        reg = self.telemetry
        if reg is not None:
            reg.counter("resilience/sdc_audits").inc()
        if res.ok:
            self.sdc_suspect_devices = ()  # healed / transient: un-flag
            return None
        self.sdc_suspect_devices = res.suspects
        if reg is not None:
            reg.counter("resilience/sdc_mismatches").inc()
        _tele.record_event("resilience/sdc_quarantine",
                           step=self.global_steps,
                           suspect_devices=list(res.suspects),
                           mismatched_groups=res.mismatched_groups)
        logger.error(
            "SDC audit: %d/%d replica groups disagree; suspect device(s) "
            "%s quarantined", res.mismatched_groups, res.n_groups,
            list(res.suspects))
        if self._sdc_quarantine_cb is not None:
            try:
                self._sdc_quarantine_cb(res)
            except Exception as e:
                logger.warning("sdc quarantine callback failed: %s", e)
        detail = (f"{res.mismatched_groups}/{res.n_groups} replica groups "
                  f"disagree; suspects {list(res.suspects)}")
        return TrainingAnomaly(AnomalyClass.SDC, self.global_steps,
                               float(res.mismatched_groups), 0.0, detail)

    def set_sdc_quarantine_callback(self, cb):
        """Hook for the elastic agent / launcher: called with the
        :class:`~deepspeed_tpu.runtime.sentinel.SDCAuditResult` when an
        audit finds a deviating replica, so the supervisor can exclude the
        host from the next worker group."""
        self._sdc_quarantine_cb = cb

    def set_slo(self, slo) -> None:
        """Attach an :class:`~deepspeed_tpu.telemetry.slo.SLOEngine`
        (ISSUE 13): the training SLIs (``train_mfu`` floor,
        ``train_anomaly_rate``) are evaluated at the sentinel's check
        fence, where the gauges/counters they read were just refreshed.
        Requires the resilience sentinel to be armed (the fence is the
        evaluation site); raises otherwise so a misconfigured job fails
        loudly instead of silently never judging."""
        if slo is not None and self.sentinel is None:
            raise ValueError(
                "set_slo needs the resilience sentinel armed "
                "(resilience.enabled): SLO evaluation rides the "
                "sentinel's check fence")
        self.slo = slo

    def _sdc_step_replay_check(self, batch):
        """Single-host determinism probe: the compiled step run twice from
        bit-identical state copies must agree bit-exactly; a mismatch is
        flaky hardware (counted + evented, recovered like SDC)."""
        from deepspeed_tpu import telemetry as _tele
        from deepspeed_tpu.runtime.sentinel import (
            AnomalyClass, TrainingAnomaly, step_replay_probe)

        if (self._compiled_train_step is None or self._host_opt is not None
                or not getattr(self, "_step_takes_extra_args", False)
                or self._use_pld or self._use_random_ltd):
            return None
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        rng = jax.random.fold_in(self._dropout_rng, self.global_steps)
        ok, detail = step_replay_probe(
            self._compiled_train_step, self.state, self.state_shardings,
            args=(batch, lr, rng, None, None))
        reg = self.telemetry
        if reg is not None:
            reg.counter("resilience/step_replays").inc()
        if ok:
            return None
        if reg is not None:
            reg.counter("resilience/step_replay_mismatches").inc()
        _tele.record_event("resilience/step_replay_mismatch",
                           step=self.global_steps, detail=detail)
        logger.error("step-replay probe: %s", detail)
        return TrainingAnomaly(AnomalyClass.REPLAY, self.global_steps,
                               0.0, 0.0, detail)

    def _recover_or_raise(self, anomaly):
        """PaLM-style rewind-and-skip: reload the newest *valid* checkpoint
        (PR 1's walk-back survives a tag corrupted mid-recovery), restore
        the dataloader position from its ``__meta__``, then fast-forward
        past the offending batch window — the batches between the rewind
        target and the anomaly, plus an extra width that escalates across
        back-to-back rewinds. SDC/replay anomalies skip nothing (the data
        was fine): they rewind and deterministically replay. Bounded by
        the rolling rewind budget so a poisoned shard cannot livelock."""
        from deepspeed_tpu import telemetry as _tele
        from deepspeed_tpu.runtime.sentinel import (
            AnomalyClass, RewindBudgetExceededError, TrainingAnomalyError)

        rcfg = self.resilience_config
        _tele.record_event("resilience/anomaly", cls=anomaly.cls,
                           step=anomaly.step, value=anomaly.value,
                           zscore=round(anomaly.zscore, 2),
                           detail=anomaly.detail)
        if self.flight_recorder is not None:
            # freeze the pre-incident window BEFORE recovery rewinds
            # state — the dump is the postmortem of what training saw
            # at detection, not of the already-healed timeline
            self.flight_recorder.trigger(
                "training_anomaly", cls=anomaly.cls, step=anomaly.step,
                value=anomaly.value, zscore=round(anomaly.zscore, 2),
                detail=anomaly.detail)
        logger.warning("training anomaly: %s at step %d (%s)",
                       anomaly.cls, anomaly.step, anomaly.detail)
        dl = self.training_dataloader
        recoverable = (rcfg.on_anomaly == "recover"
                       and rcfg.checkpoint_dir is not None
                       # the engine-owned loader must be the LIVE source:
                       # rewinding it while a caller-supplied iterator
                       # keeps advancing would silently desync data from
                       # params — raise instead
                       and getattr(self, "_engine_owned_stream", False)
                       and dl is not None
                       and hasattr(dl, "load_state_dict")
                       and getattr(dl, "supports_deterministic_resume",
                                   lambda: True)())
        if not recoverable:
            raise TrainingAnomalyError(anomaly)
        t0 = time.perf_counter()
        spent = self._rewind_budget.record()
        if spent > rcfg.max_rewinds:
            _tele.record_event("resilience/rewind_budget_exhausted",
                               spent=spent, budget=rcfg.max_rewinds)
            raise RewindBudgetExceededError(
                anomaly, f"rewind budget exhausted: {spent} rewinds "
                         f"(budget {rcfg.max_rewinds}"
                         + (f" in {rcfg.rewind_window_s}s"
                            if rcfg.rewind_window_s else "")
                         + f"); last anomaly: {anomaly.cls} at step "
                           f"{anomaly.step}")
        # rewind: auto-resume walk-back to the newest valid tag; raises the
        # typed CheckpointCorruptionError loudly if every tag is invalid
        it_before = getattr(self, "_train_iter", None)
        path, _ = self.load_checkpoint(rcfg.checkpoint_dir)
        if path is None:
            raise TrainingAnomalyError(
                anomaly, f"anomaly at step {anomaly.step} but no checkpoint "
                         f"under {rcfg.checkpoint_dir} to rewind to")
        if it_before is not None and \
                getattr(self, "_train_iter", None) is it_before:
            # the loaded tag carried no restorable dataloader state (saved
            # pre-ISSUE-10, or before the loader was attached): params are
            # rewound but the data stream is NOT — fast-forwarding the
            # stale iterator would silently desync data from params
            raise TrainingAnomalyError(
                anomaly, f"rewound params to {path}, but that checkpoint "
                         f"has no dataloader state — cannot rewind the "
                         f"data stream deterministically; re-save "
                         f"checkpoints with this engine to enable "
                         f"auto-recovery")
        rewound_to = self.global_steps
        self._rewinds_since_clean += 1
        self._last_anomaly_step = anomaly.step
        if anomaly.cls in AnomalyClass.DATA_CLASSES:
            extra = min(rcfg.skip_width_base * rcfg.skip_width_factor
                        ** (self._rewinds_since_clean - 1),
                        rcfg.skip_width_max)
            skip_steps = max(anomaly.step - rewound_to, 0) + extra
        else:  # sdc/replay: the data was fine — replay it
            skip_steps = 0
        n_batches = skip_steps * self.gas
        it = self._ensure_train_iter()  # load invalidated the old iterator
        for _ in range(n_batches):
            next(it)
        # sentinel history is kept: the rewind RESTORES the pre-anomaly
        # regime, so that history is the correct baseline for the replayed
        # steps — resetting would open a min_history blind spot right
        # where a widened second skip may be needed. (The anomalous value
        # itself was never pushed.)
        self._pending_anomaly_reads.clear()
        dt_ms = (time.perf_counter() - t0) * 1e3
        rec = {"class": anomaly.cls, "anomaly_step": anomaly.step,
               "rewound_to": rewound_to, "skipped_steps": skip_steps,
               "skipped_batches": n_batches, "checkpoint": path,
               "recovery_ms": round(dt_ms, 2)}
        self.rewind_log.append(rec)
        reg = self.telemetry
        if reg is not None:
            reg.counter("resilience/rewinds").inc()
            if n_batches:
                reg.counter("resilience/skipped_batches").inc(n_batches)
            reg.histogram("resilience/recovery_latency_ms").observe(dt_ms)
        if self.tracer is not None:
            self.tracer.record(
                "recovery", t0, time.perf_counter(),
                trace_id=self._train_trace, anomaly=anomaly.cls,
                anomaly_step=anomaly.step, rewound_to=rewound_to,
                skipped_batches=n_batches)
        _tele.record_event("resilience/rewind", **rec)
        log_dist(
            f"anomaly recovery: {anomaly.cls} at step {anomaly.step} -> "
            f"rewound to step {rewound_to} ({path}), skipping "
            f"{n_batches} batch(es) ({skip_steps} step(s)), "
            f"{dt_ms:.0f} ms", ranks=[0])

    def destroy(self):
        """Engine shutdown (reference engine.destroy): emit the comms
        summary when comms logging is enabled, flush telemetry, close the
        engine-owned JSONL sink."""
        import deepspeed_tpu.comm as dist

        if self.config.comms_logger_config.enabled:
            dist.log_summary()
        if self.telemetry is not None:
            self.telemetry.flush(step=self.global_steps)
        if self._spans_sink is not None:
            self._spans_sink.close()
            self._spans_sink = None
        if self._owned_sink is not None:
            self._owned_sink.close()
            self._owned_sink = None
        if self._attached_sink is not None:
            if self.telemetry is not None and \
                    self.telemetry.sink is self._attached_sink:
                # detach whatever THIS engine attached — the bare owned
                # sink, or the flight-recorder tee wrapping it — so the
                # process-global registry never keeps writing through a
                # closed sink (or a dead engine's recorder) afterwards
                self.telemetry.attach_sink(None)
            self._attached_sink = None

    # ------------------------------------------ forward/backward/step parity
    def forward(self, batch):
        """Compute loss for one microbatch; grads are computed in the same
        compiled program and cached for backward() (JAX has no separate
        autograd pass — doc'd divergence from reference forward:1614)."""
        if self._compiled_micro_grad is None:
            def micro(state_params, scaler, batch, rng):
                return self._micro_loss_and_grads(state_params, batch, scaler.cur_scale, rng)
            self._compiled_micro_grad = jax.jit(micro)
        self.timers(FORWARD_GLOBAL_TIMER).start()
        rng = jax.random.fold_in(self._dropout_rng, self.micro_steps)
        batch = jax.device_put(batch, self._batch_shardings(batch))
        with jax.profiler.TraceAnnotation("dstpu/forward"):
            scaled_loss, grads, metrics = self._compiled_micro_grad(
                self.state.params, self.state.scaler, batch, rng)
        self._pending = (scaled_loss, grads)
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return metrics["loss"]

    __call__ = forward

    def backward(self, loss=None, allreduce_gradients: bool = True):
        """Accumulate the cached grads (reference backward:1755 + grad hooks)."""
        assert getattr(self, "_pending", None) is not None, \
            "backward() must follow forward()"
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        _, grads = self._pending
        self._pending = None
        with jax.profiler.TraceAnnotation("dstpu/backward"):
            if self._grad_acc is None:
                self._grad_acc = grads
            else:
                add = jax.jit(lambda a, b: jax.tree_util.tree_map(jnp.add, a, b))
                self._grad_acc = add(self._grad_acc, grads)
        self._acc_count += 1
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        return (self.micro_steps + 1) % self.gas == 0

    def step(self):
        """Apply optimizer at gas boundary (reference step:1951)."""
        self.timers(STEP_GLOBAL_TIMER).start()
        at_boundary = self.is_gradient_accumulation_boundary()
        if at_boundary and self._host_opt is not None:
            assert self._acc_count == self.gas, (
                f"step() at boundary needs {self.gas} backward() calls, "
                f"got {self._acc_count}")
            if getattr(self, "_compiled_prep_grads", None) is None:
                self._compiled_prep_grads = jax.jit(
                    self._unscale_epilogue, donate_argnums=(0,))
            grads, overflow, norm = self._compiled_prep_grads(
                self._grad_acc, self.state.scaler)
            self._host_apply(grads, bool(jax.device_get(overflow)),  # dstpu-lint: fence=host-optimizer path: boundary apply is host-side
                             float(jax.device_get(norm)), self.get_lr()[0])
            self._grad_acc = None
            self._acc_count = 0
            self._global_grad_norm = norm
            self.global_steps += 1
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
            if self.progressive_layer_drop is not None:
                self.progressive_layer_drop.update_state(self.global_steps)
            self.micro_steps += 1
            self.timers(STEP_GLOBAL_TIMER).stop()
            return
        if at_boundary:
            assert self._acc_count == self.gas, (
                f"step() at boundary needs {self.gas} backward() calls, "
                f"got {self._acc_count}")
            if self._compiled_apply_grads is None:
                def apply_fn(state, grads, lr):
                    grads = jax.tree_util.tree_map(lambda g: g / self.gas, grads)
                    new_state, overflow, norm = self._apply_grads(state, grads, lr)
                    return new_state, overflow, norm
                self._compiled_apply_grads = jax.jit(apply_fn, donate_argnums=(0, 1))
            lr = jnp.asarray(self.get_lr()[0], jnp.float32)
            with jax.profiler.TraceAnnotation("dstpu/optimizer_step"):
                self.state, overflow, norm = self._compiled_apply_grads(
                    self.state, self._grad_acc, lr)
            self._grad_acc = None
            self._acc_count = 0
            self._global_grad_norm = norm
            self.global_steps += 1
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
            if self.progressive_layer_drop is not None:
                self.progressive_layer_drop.update_state(self.global_steps)
        self.micro_steps += 1
        self.timers(STEP_GLOBAL_TIMER).stop()

    # -------------------------------------------------------------- eval path
    def eval_batch(self, batch):
        if self._compiled_eval is None:
            def ev(params, batch):
                cparams = self._cast_for_compute(params)
                loss, metrics = self.module.apply(cparams, batch, rngs=None, train=False)
                return loss
            self._compiled_eval = jax.jit(ev)
        batch = jax.device_put(batch, self._batch_shardings(batch))
        return self._compiled_eval(self.state.params, batch)

    # ------------------------------------------------------------- accessors
    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_last_lr()
        return [getattr(self.optimizer, "lr", 1e-3)]

    def get_global_grad_norm(self):
        return None if self._global_grad_norm is None else float(
            # dstpu-lint: fence=user-facing accessor, not on the step path
            jax.device_get(self._global_grad_norm))

    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    def gradient_accumulation_steps(self) -> int:
        return self.gas

    def zero_optimization_stage(self) -> int:
        return self.zero_stage

    @property
    def params(self):
        return self.state.params

    def get_loss_scale(self):
        # dstpu-lint: fence=user-facing accessor, not on the step path
        return float(jax.device_get(self.state.scaler.cur_scale))

    # --------------------------------------------------------------- data io
    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None, **kw):
        from deepspeed_tpu.runtime.dataloader import build_dataloader

        if batch_size is None:
            # per-process batch: micro_batch * local share of the dense batch axes
            batch_size = self.config.train_micro_batch_size_per_gpu * (
                self.topology.data_parallel_size // max(jax.process_count(), 1))
        return build_dataloader(dataset, batch_size, config=self.config,
                                collate_fn=collate_fn, **kw)

    # ----------------------------------------------------------- checkpoints
    def _checkpoint_engine(self):
        """Engine-lifetime checkpoint backend; async when configured
        (reference Nebula engine selection)."""
        if getattr(self, "_ckpt_engine", None) is None:
            if self.config.checkpoint_config.async_save:
                from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (
                    AsyncCheckpointEngine)

                self._ckpt_engine = AsyncCheckpointEngine()
            else:
                self._ckpt_engine = None  # default NativeCheckpointEngine
        return self._ckpt_engine

    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True):
        from deepspeed_tpu.runtime.checkpoint_engine.engine import save_engine_checkpoint

        t0 = time.perf_counter()
        try:
            return save_engine_checkpoint(self, save_dir, tag=tag,
                                          client_state=client_state,
                                          save_latest=save_latest,
                                          checkpoint_engine=self._checkpoint_engine())
        finally:
            if self.tracer is not None:
                self.tracer.record("checkpoint_save", t0,
                                   time.perf_counter(),
                                   trace_id=self._train_trace,
                                   step=self.global_steps)
            if self.telemetry is not None:
                self._reset_telemetry_window()

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        load_lr_scheduler_states=True, load_module_only=False):
        from deepspeed_tpu.runtime.checkpoint_engine.engine import load_engine_checkpoint

        t0 = time.perf_counter()
        try:
            return load_engine_checkpoint(self, load_dir, tag=tag,
                                          load_optimizer_states=load_optimizer_states,
                                          load_lr_scheduler_states=load_lr_scheduler_states,
                                          load_module_only=load_module_only,
                                          checkpoint_engine=self._checkpoint_engine())
        finally:
            if self.tracer is not None:
                self.tracer.record("checkpoint_load", t0,
                                   time.perf_counter(),
                                   trace_id=self._train_trace,
                                   step=self.global_steps)
            if self.telemetry is not None:
                self._reset_telemetry_window()

    def save_16bit_model(self, save_dir, save_filename="model_weights.npz"):
        from deepspeed_tpu.runtime.checkpoint_engine.engine import save_16bit_model

        return save_16bit_model(self, save_dir, save_filename)

    def install_preemption_handler(self, save_dir, tag=None, defer=None,
                                   **handler_kw):
        """SIGTERM (TPU maintenance/preemption notice) → final synchronous
        checkpoint to ``save_dir`` → exit with the restartable preemption
        code, which the elastic agent restarts without burning budget.
        Returns the installed handler (also usable as a maintenance-event
        callback via ``handler.trigger()``).

        On multi-host meshes the final save is deferred to the next step
        boundary (the engine polls the handler each train step): the save's
        gather collectives must not launch from an arbitrary
        signal-interrupt point where they could interleave with in-flight
        step collectives differently on each host. Single-host defaults to
        immediate. Override via ``defer``."""
        from deepspeed_tpu.elasticity.preemption import PreemptionHandler

        def final_save():
            self.save_checkpoint(save_dir, tag=tag)
            ck = self._checkpoint_engine()
            if ck is not None and hasattr(ck, "wait"):
                ck.wait()  # async engine: durable before the process dies

        if defer is None:
            defer = jax.process_count() > 1
        if defer and jax.process_count() > 1 and \
                "consensus_fn" not in handler_kw:
            # per-step scalar allgather: hosts agree who saw a notice, so
            # the save's collectives start on every host at the SAME step
            # boundary — the cost is opt-in (handler installed) and tiny
            def consensus(local_flag):
                from jax.experimental import multihost_utils

                votes = multihost_utils.process_allgather(
                    np.int32(bool(local_flag)))
                return bool(np.max(votes))

            handler_kw["consensus_fn"] = consensus
        self._preemption_handler = PreemptionHandler(
            final_save, defer=defer, **handler_kw).install()
        return self._preemption_handler
