"""ZeRO-Offload / ZeRO-Infinity host optimizer.

Reference analog: the CPU-offload paths of ``DeepSpeedZeroOptimizer``
(stage_1_and_2.py:1031-1156) and the stage-3 sub-group step with NVMe swap
(stage3.py:1735, swap_tensor/*): fp32 master params + Adam moments live in
host memory (or on NVMe), gradients stream to the host each step, the update
runs on the CPU via the native vectorized kernel
(csrc/adam/dstpu_cpu_adam.cpp), and the refreshed compute-dtype params are
pushed back to the device.

Memory story (matches the reference): HBM holds only compute-dtype params
(+ activations); host RAM holds 12 bytes/param fp32 state (4 master + 8
moments); with ``device="nvme"`` the moments+master per-leaf "sub-groups"
live on disk and are swapped in/out around each leaf's update with
read/step/writeback overlap (PipelinedOptimizerSwapper).

Single-host semantics: grads arrive as fully-addressable JAX arrays
(device_get gathers the global value).  Multi-host sharding of the host
state follows the same design with per-process shard slicing — tracked as a
TODO at the engine level, not here.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


class HostOffloadOptimizer:
    """Host-resident Adam/AdamW/Adagrad with optional NVMe state residency.

    Functional surface intentionally differs from the device optimizers: the
    state lives *inside* this object (host numpy), and ``step`` consumes
    device grads + returns device-ready compute-dtype params.
    """

    def __init__(self, optimizer, offload_config, compute_dtype,
                 param_shapes=None):
        self.opt = optimizer
        self.compute_dtype = compute_dtype
        self.device = getattr(offload_config, "device", "cpu")
        self.kind = getattr(optimizer, "name", "adam")
        if self.kind not in ("adam", "cpu_adam", "adagrad"):
            raise ValueError(
                f"host offload supports adam/adamw/adagrad, got '{self.kind}'")
        self._use_native = None  # resolved lazily (C++ toolchain probe)
        self.step_count = 0
        self.master: Dict[str, np.ndarray] = {}
        self.moments: Dict[str, Dict[str, np.ndarray]] = {}
        self._swapper = None
        self._swap_names: List[str] = []
        if self.device == "nvme":  # OffloadDeviceEnum is a str mixin
            # per-run unique default: a fixed shared path would let concurrent
            # jobs overwrite each other's swapped optimizer state
            folder = getattr(offload_config, "nvme_path", None) or \
                tempfile.mkdtemp(prefix="dstpu_nvme_swap_")
            from deepspeed_tpu.runtime.swap_tensor import (
                PipelinedOptimizerSwapper)

            self._swapper = PipelinedOptimizerSwapper(folder)

    # ------------------------------------------------------------------ init
    def init(self, params_device) -> None:
        """Pull fp32 masters to host; zero moments; optionally spill to NVMe.
        (Re-)initialising resets the Adam step so bias correction restarts
        with the fresh moments."""
        self.step_count = 0
        flat = _flatten_with_paths(params_device)
        host = jax.device_get(flat)
        for i, (name, arr) in enumerate(host.items()):
            master = np.asarray(arr, np.float32)
            moments = self._zero_moments(master)
            if self._swapper is not None:
                state = {"master": master, **moments}
                self._swapper.swap_out_group(i, state)
                self._swap_names = ["master"] + list(moments)
            else:
                self.master[name] = master
                self.moments[name] = moments
        self._names = list(host.keys())

    def _zero_moments(self, master: np.ndarray) -> Dict[str, np.ndarray]:
        if self.kind in ("adam", "cpu_adam"):
            return {"exp_avg": np.zeros_like(master),
                    "exp_avg_sq": np.zeros_like(master)}
        return {"sum_sq": np.zeros_like(master)}

    # ------------------------------------------------------------------ step
    def step(self, grads_host: Dict[str, np.ndarray], lr: float,
             grad_scale: float = 1.0) -> Dict[str, np.ndarray]:
        """Update masters in place; returns compute-dtype param images.

        ``grad_scale`` multiplies grads before the update (combined
        unscale+clip factor computed by the engine).
        """
        self.step_count += 1
        out: Dict[str, np.ndarray] = {}
        if self._swapper is not None:
            groups = list(range(len(self._names)))

            def step_fn(g, state):
                name = self._names[g]
                grad = self._prep_grad(grads_host[name], grad_scale)
                self._kernel(state["master"], grad, state, lr)
                out[name] = self._to_compute(state["master"])

            self._swapper.run_step(groups, self._swap_names, step_fn)
        else:
            for name in self._names:
                grad = self._prep_grad(grads_host[name], grad_scale)
                state = {"master": self.master[name], **self.moments[name]}
                self._kernel(self.master[name], grad, state, lr)
                out[name] = self._to_compute(self.master[name])
        return out

    def _prep_grad(self, grad: np.ndarray, grad_scale: float) -> np.ndarray:
        g = np.asarray(grad, np.float32).reshape(-1)
        if grad_scale != 1.0:
            g = g * np.float32(grad_scale)
        return np.ascontiguousarray(g)

    def _kernel(self, master: np.ndarray, grad: np.ndarray,
                state: Dict[str, np.ndarray], lr: float) -> None:
        flat = master.reshape(-1)
        if self._native_ok():
            from deepspeed_tpu.ops import cpu_adam_native as cna

            if self.kind in ("adam", "cpu_adam"):
                cna.adam_step(flat, grad, state["exp_avg"].reshape(-1),
                              state["exp_avg_sq"].reshape(-1),
                              step=self.step_count, lr=lr_f(lr),
                              betas=self.opt.betas, eps=self.opt.eps,
                              weight_decay=self.opt.weight_decay,
                              adamw_mode=getattr(self.opt, "adam_w_mode", True),
                              bias_correction=getattr(self.opt, "bias_correction", True))
            else:
                cna.adagrad_step(flat, grad, state["sum_sq"].reshape(-1),
                                 lr=lr_f(lr), eps=self.opt.eps,
                                 weight_decay=self.opt.weight_decay)
        else:  # numpy fallback (no C++ toolchain)
            if self.kind in ("adam", "cpu_adam"):
                b1, b2 = self.opt.betas
                adamw = getattr(self.opt, "adam_w_mode", True)
                if self.opt.weight_decay > 0 and not adamw:
                    grad = grad + self.opt.weight_decay * flat  # true L2
                m, v = state["exp_avg"].reshape(-1), state["exp_avg_sq"].reshape(-1)
                m[:] = b1 * m + (1 - b1) * grad
                v[:] = b2 * v + (1 - b2) * grad * grad
                bc1 = 1 - b1 ** self.step_count
                bc2 = 1 - b2 ** self.step_count
                upd = (m / bc1) / (np.sqrt(v / bc2) + self.opt.eps)
                if self.opt.weight_decay > 0 and adamw:
                    upd = upd + self.opt.weight_decay * flat
                flat -= lr_f(lr) * upd
            else:
                s = state["sum_sq"].reshape(-1)
                g = grad + self.opt.weight_decay * flat
                s += g * g
                flat -= lr_f(lr) * g / (np.sqrt(s) + self.opt.eps)

    def _native_ok(self) -> bool:
        if self._use_native is None:
            try:
                from deepspeed_tpu.ops import cpu_adam_native as cna

                self._use_native = cna.available()
            except Exception:
                self._use_native = False
            if not self._use_native:
                logger.warning("cpu_adam_native unavailable; host optimizer "
                               "falls back to numpy")
        return self._use_native

    def _to_compute(self, master: np.ndarray) -> np.ndarray:
        import ml_dtypes

        if self.compute_dtype == np.float32 or str(self.compute_dtype) == "float32":
            return master
        name = getattr(self.compute_dtype, "__name__", str(self.compute_dtype))
        if "bfloat16" in name and self._native_ok():
            from deepspeed_tpu.ops import cpu_adam_native as cna

            return cna.copy_f32_to_bf16(master).reshape(master.shape)
        np_dtype = {"bfloat16": ml_dtypes.bfloat16,
                    "float16": np.float16}.get(name.replace("jnp.", ""), np.float32)
        return master.astype(np_dtype)

    # ----------------------------------------------------------- state (ckpt)
    def state_template(self) -> Dict[str, Any]:
        """Shapes/dtypes of the state tree WITHOUT reading swapped data
        (checkpoint-load unflatten template; np.empty does no IO)."""
        names = ["master"] + list(self._zero_moments(np.empty(0, np.float32)))
        out: Dict[str, Any] = {}
        for i, name in enumerate(self._names):
            if self._swapper is not None:
                shape, dtype = self._swapper.swapper.meta(
                    self._swapper._key(i, "master"))
                out[name] = {k: np.empty(shape, dtype) for k in names}
            else:
                out[name] = {"master": self.master[name],
                             **self.moments[name]}
        return out

    def state_dict(self) -> Dict[str, Any]:
        if self._swapper is not None:
            state = {}
            for i, name in enumerate(self._names):
                back = self._swapper.swap_in_group(i, self._swap_names)
                state[name] = dict(back)
            return {"step": self.step_count, "state": state}
        return {"step": self.step_count,
                "state": {n: {"master": self.master[n], **self.moments[n]}
                          for n in self._names}}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.step_count = int(sd["step"])
        for i, name in enumerate(self._names):
            entry = sd["state"][name]
            if self._swapper is not None:
                self._swapper.swap_out_group(i, {k: np.asarray(v)
                                                 for k, v in entry.items()})
            else:
                self.master[name] = np.asarray(entry["master"], np.float32)
                self.moments[name] = {k: np.asarray(v, np.float32)
                                      for k, v in entry.items() if k != "master"}


def lr_f(lr) -> float:
    return float(np.asarray(lr))


def _flatten_with_paths(tree) -> Dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}
