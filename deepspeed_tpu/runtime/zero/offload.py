"""ZeRO-Offload / ZeRO-Infinity host optimizer.

Reference analog: the CPU-offload paths of ``DeepSpeedZeroOptimizer``
(stage_1_and_2.py:1031-1156) and the stage-3 sub-group step with NVMe swap
(stage3.py:1735, swap_tensor/*): fp32 master params + Adam moments live in
host memory (or on NVMe), gradients stream to the host each step, the update
runs on the CPU via the native vectorized kernel
(csrc/adam/dstpu_cpu_adam.cpp), and the refreshed compute-dtype params are
pushed back to the device.

Memory story (matches the reference): HBM holds only compute-dtype params
(+ activations); host RAM holds 12 bytes/param fp32 state (4 master + 8
moments); with ``device="nvme"`` the moments+master per-leaf "sub-groups"
live on disk and are swapped in/out around each leaf's update with
read/step/writeback overlap (PipelinedOptimizerSwapper).

Multi-host semantics: when a param is NOT fully addressable from this
process (a true multi-host mesh), its host master is the concatenation of
this process's UNIQUE addressable shards (dedup by shard index — replicas
are stored once), gradients are pulled shard-wise in the same layout, and
the refreshed compute-dtype images are reassembled into global arrays via
``jax.make_array_from_single_device_arrays``.  Each host therefore holds
only ~1/process_count of the 12 bytes/param state, the way the reference
partitions cpu-offloaded optimizer state across ranks
(stage_1_and_2.py:1031).  The same shard path can be forced on one host
with ``DSTPU_FORCE_SHARD_OFFLOAD=1`` (that is how it is unit-tested).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _index_key(index) -> Tuple:
    """Hashable key for a shard's global index (tuple of slices)."""
    return tuple((s.start, s.stop, s.step) for s in index)


class _ShardMeta:
    """Layout of one param's process-local host state: ordered unique
    shards (index, local shape, owning devices) + the global shape."""

    def __init__(self, global_shape, parts):
        self.global_shape = tuple(global_shape)
        self.parts = parts     # [(key, index, shape, [devices])]

    def collect(self, arr: "jax.Array", sink: List) -> List[int]:
        """Append ``arr``'s unique local shard buffers to ``sink`` (in this
        meta's order) and return their slot indices — the caller batches
        ONE device_get over all params' shards."""
        by_key = {}
        for s in arr.addressable_shards:
            by_key.setdefault(_index_key(s.index), s.data)
        missing = [k for (k, *_rest) in self.parts if k not in by_key]
        if missing:
            raise ValueError(
                "gradient shard layout does not match the master layout "
                f"(missing indices {missing[:2]}...); the engine must "
                "constrain grads to the master sharding before offload")
        slots = []
        for (k, *_r) in self.parts:
            slots.append(len(sink))
            sink.append(by_key[k])
        return slots


def _is_shardable(leaf) -> bool:
    """Only real jax Arrays enter shard-local storage (tests monkeypatch
    this to inject fake partial-ownership shard views)."""
    return isinstance(leaf, jax.Array)


def _leaf_meta(leaf, force_sharded: bool):
    """leaf → _ShardMeta for shard-local storage, or None for dense.
    Reads only shard metadata (shapes/indices/devices) — no transfers."""
    if _is_shardable(leaf) and (force_sharded or
                                not leaf.is_fully_addressable):
        uniq: Dict[Tuple, Any] = {}
        devices: Dict[Tuple, List] = {}
        for s in leaf.addressable_shards:
            k = _index_key(s.index)
            devices.setdefault(k, []).append(s.device)
            if k not in uniq:
                uniq[k] = (s.index, tuple(s.data.shape))
        parts = [(k, idx, shape, devices[k])
                 for k, (idx, shape) in uniq.items()]
        return _ShardMeta(leaf.shape, parts)
    return None


class HostOffloadOptimizer:
    """Host-resident Adam/AdamW/Adagrad with optional NVMe state residency.

    Functional surface intentionally differs from the device optimizers: the
    state lives *inside* this object (host numpy), and ``step`` consumes
    device grads + returns device-ready compute-dtype params.
    """

    def __init__(self, optimizer, offload_config, compute_dtype,
                 param_shapes=None):
        self.opt = optimizer
        self.compute_dtype = compute_dtype
        self.device = getattr(offload_config, "device", "cpu")
        self.kind = getattr(optimizer, "name", "adam")
        if self.kind not in ("adam", "cpu_adam", "adagrad"):
            raise ValueError(
                f"host offload supports adam/adamw/adagrad, got '{self.kind}'")
        self._use_native = None  # resolved lazily (C++ toolchain probe)
        self.step_count = 0
        self.master: Dict[str, np.ndarray] = {}
        self.moments: Dict[str, Dict[str, np.ndarray]] = {}
        self._swapper = None
        self._swap_names: List[str] = []
        if self.device == "nvme":  # OffloadDeviceEnum is a str mixin
            # per-run unique default: a fixed shared path would let concurrent
            # jobs overwrite each other's swapped optimizer state
            folder = getattr(offload_config, "nvme_path", None) or \
                tempfile.mkdtemp(prefix="dstpu_nvme_swap_")
            from deepspeed_tpu.runtime.swap_tensor import (
                PipelinedOptimizerSwapper)

            self._swapper = PipelinedOptimizerSwapper(folder)

    # ------------------------------------------------------------------ init
    def init(self, params_device) -> None:
        """Pull fp32 masters to host; zero moments; optionally spill to NVMe.
        (Re-)initialising resets the Adam step so bias correction restarts
        with the fresh moments.  Non-fully-addressable params keep only
        this process's unique shards (flat layout, see _ShardMeta)."""
        self.step_count = 0
        force = os.environ.get("DSTPU_FORCE_SHARD_OFFLOAD") == "1"
        flat = _flatten_with_paths(params_device)
        self._shard_meta: Dict[str, Optional[_ShardMeta]] = {}
        sink: List[Any] = []           # ONE batched D2H over all leaves
        slots: Dict[str, Any] = {}
        for name, leaf in flat.items():
            meta = _leaf_meta(leaf, force)
            self._shard_meta[name] = meta
            if meta is None:
                slots[name] = len(sink)
                sink.append(leaf)
            else:
                slots[name] = meta.collect(leaf, sink)
        host_bufs = jax.device_get(sink)
        host = {}
        for name in flat:
            s = slots[name]
            host[name] = np.asarray(host_bufs[s]) if isinstance(s, int) \
                else np.concatenate([np.asarray(host_bufs[i]).reshape(-1)
                                     for i in s])
        for i, (name, arr) in enumerate(host.items()):
            master = np.asarray(arr, np.float32)
            moments = self._zero_moments(master)
            if self._swapper is not None:
                state = {"master": master, **moments}
                self._swapper.swap_out_group(i, state)
                self._swap_names = ["master"] + list(moments)
            else:
                self.master[name] = master
                self.moments[name] = moments
        self._names = list(host.keys())

    def _zero_moments(self, master: np.ndarray) -> Dict[str, np.ndarray]:
        if self.kind in ("adam", "cpu_adam"):
            return {"exp_avg": np.zeros_like(master),
                    "exp_avg_sq": np.zeros_like(master)}
        return {"sum_sq": np.zeros_like(master)}

    # ------------------------------------------------------------------ step
    def step(self, grads_host: Dict[str, np.ndarray], lr: float,
             grad_scale: float = 1.0) -> Dict[str, np.ndarray]:
        """Update masters in place; returns compute-dtype param images.

        ``grad_scale`` multiplies grads before the update (combined
        unscale+clip factor computed by the engine).
        """
        self.step_count += 1
        out: Dict[str, np.ndarray] = {}
        if self._swapper is not None:
            groups = list(range(len(self._names)))

            def step_fn(g, state):
                name = self._names[g]
                out[name] = self._leaf_update(state["master"],
                                              grads_host[name], state, lr,
                                              grad_scale)

            self._swapper.run_step(groups, self._swap_names, step_fn)
        else:
            for name in self._names:
                state = {"master": self.master[name], **self.moments[name]}
                out[name] = self._leaf_update(self.master[name],
                                              grads_host[name], state, lr,
                                              grad_scale)
        return out

    def _leaf_update(self, master: np.ndarray, grad: np.ndarray,
                     state: Dict[str, np.ndarray], lr,
                     grad_scale: float) -> np.ndarray:
        """One param's update → compute-dtype image.  The Adam+native path is
        a single fused memory sweep (bf16/fp32 grads decoded + scaled inline,
        moments+master updated, bf16 image emitted) — the separate
        convert/scale/step/image passes ran the 1.3B host step at ~0.7 GB/s
        (round-2 weak #4; reference csrc/adam/cpu_adam.cpp:309 fuses the
        fp16 param copy into the step for the same reason)."""
        if self.kind in ("adam", "cpu_adam") and self._native_ok():
            from deepspeed_tpu.ops import cpu_adam_native as cna

            dt = getattr(self.compute_dtype, "__name__",
                         str(self.compute_dtype))
            emit_bf16 = "bfloat16" in dt
            img = cna.adam_step_fused(
                master.reshape(-1), np.asarray(grad).reshape(-1),
                state["exp_avg"].reshape(-1), state["exp_avg_sq"].reshape(-1),
                step=self.step_count, lr=lr_f(lr), betas=self.opt.betas,
                eps=self.opt.eps, weight_decay=self.opt.weight_decay,
                adamw_mode=getattr(self.opt, "adam_w_mode", True),
                bias_correction=getattr(self.opt, "bias_correction", True),
                grad_scale=grad_scale, emit_bf16=emit_bf16)
            return img.reshape(master.shape) if emit_bf16 \
                else self._to_compute(master)
        grad = self._prep_grad(grad, grad_scale)
        self._kernel(master, grad, state, lr)
        return self._to_compute(master)

    def grads_to_host(self, grads_tree) -> Dict[str, np.ndarray]:
        """Device grads → host arrays in the masters' layout (global dense
        for fully-addressable params, ordered local shards otherwise).
        All transfers ride ONE batched device_get."""
        flat = _flatten_with_paths(grads_tree)
        dense = {n: leaf for n, leaf in flat.items()
                 if self._shard_meta.get(n) is None}
        shard_bufs: List[Any] = []
        slots: Dict[str, List[int]] = {}
        for name, leaf in flat.items():
            meta = self._shard_meta.get(name)
            if meta is not None:
                slots[name] = meta.collect(leaf, shard_bufs)
        host_dense, host_bufs = jax.device_get((dense, shard_bufs))
        out: Dict[str, np.ndarray] = {}
        for name in flat:
            if name in slots:
                out[name] = np.concatenate(
                    [np.asarray(host_bufs[i]).reshape(-1)
                     for i in slots[name]])
            else:
                out[name] = host_dense[name]
        return out

    def images_to_device(self, images: Dict[str, np.ndarray], treedef,
                         shardings):
        """Updated compute-dtype images → device param tree.  Sharded
        entries are rebuilt as global arrays from per-device buffers."""
        shard_leaves = treedef.flatten_up_to(shardings)
        arrs = []
        for name, sh in zip(self._names, shard_leaves):
            meta = self._shard_meta.get(name)
            img = images[name]
            if meta is None:
                arrs.append(jax.device_put(img, sh))
                continue
            bufs = []
            off = 0
            for (_k, _idx, shape, devices) in meta.parts:
                n = int(np.prod(shape))
                part = np.ascontiguousarray(
                    np.asarray(img)[off:off + n].reshape(shape))
                off += n
                for d in devices:
                    bufs.append(jax.device_put(part, d))
            arrs.append(jax.make_array_from_single_device_arrays(
                meta.global_shape, sh, bufs))
        return jax.tree_util.tree_unflatten(treedef, arrs)

    def _prep_grad(self, grad: np.ndarray, grad_scale: float) -> np.ndarray:
        g = np.asarray(grad, np.float32).reshape(-1)
        if grad_scale != 1.0:
            g = g * np.float32(grad_scale)
        return np.ascontiguousarray(g)

    def _kernel(self, master: np.ndarray, grad: np.ndarray,
                state: Dict[str, np.ndarray], lr: float) -> None:
        flat = master.reshape(-1)
        if self._native_ok():
            from deepspeed_tpu.ops import cpu_adam_native as cna

            if self.kind in ("adam", "cpu_adam"):
                cna.adam_step(flat, grad, state["exp_avg"].reshape(-1),
                              state["exp_avg_sq"].reshape(-1),
                              step=self.step_count, lr=lr_f(lr),
                              betas=self.opt.betas, eps=self.opt.eps,
                              weight_decay=self.opt.weight_decay,
                              adamw_mode=getattr(self.opt, "adam_w_mode", True),
                              bias_correction=getattr(self.opt, "bias_correction", True))
            else:
                cna.adagrad_step(flat, grad, state["sum_sq"].reshape(-1),
                                 lr=lr_f(lr), eps=self.opt.eps,
                                 weight_decay=self.opt.weight_decay)
        else:  # numpy fallback (no C++ toolchain)
            if self.kind in ("adam", "cpu_adam"):
                b1, b2 = self.opt.betas
                adamw = getattr(self.opt, "adam_w_mode", True)
                if self.opt.weight_decay > 0 and not adamw:
                    grad = grad + self.opt.weight_decay * flat  # true L2
                m, v = state["exp_avg"].reshape(-1), state["exp_avg_sq"].reshape(-1)
                m[:] = b1 * m + (1 - b1) * grad
                v[:] = b2 * v + (1 - b2) * grad * grad
                bc1 = 1 - b1 ** self.step_count
                bc2 = 1 - b2 ** self.step_count
                upd = (m / bc1) / (np.sqrt(v / bc2) + self.opt.eps)
                if self.opt.weight_decay > 0 and adamw:
                    upd = upd + self.opt.weight_decay * flat
                flat -= lr_f(lr) * upd
            else:
                s = state["sum_sq"].reshape(-1)
                g = grad + self.opt.weight_decay * flat
                s += g * g
                flat -= lr_f(lr) * g / (np.sqrt(s) + self.opt.eps)

    def _native_ok(self) -> bool:
        if self._use_native is None:
            try:
                from deepspeed_tpu.ops import cpu_adam_native as cna

                self._use_native = cna.available()
            except Exception:
                self._use_native = False
            if not self._use_native:
                logger.warning("cpu_adam_native unavailable; host optimizer "
                               "falls back to numpy")
        return self._use_native

    def _to_compute(self, master: np.ndarray) -> np.ndarray:
        import ml_dtypes

        if self.compute_dtype == np.float32 or str(self.compute_dtype) == "float32":
            return master
        name = getattr(self.compute_dtype, "__name__", str(self.compute_dtype))
        if "bfloat16" in name and self._native_ok():
            from deepspeed_tpu.ops import cpu_adam_native as cna

            return cna.copy_f32_to_bf16(master).reshape(master.shape)
        np_dtype = {"bfloat16": ml_dtypes.bfloat16,
                    "float16": np.float16}.get(name.replace("jnp.", ""), np.float32)
        return master.astype(np_dtype)

    # ----------------------------------------------------------- state (ckpt)
    def state_template(self) -> Dict[str, Any]:
        """Shapes/dtypes of the state tree WITHOUT reading swapped data
        (checkpoint-load unflatten template; np.empty does no IO)."""
        names = ["master"] + list(self._zero_moments(np.empty(0, np.float32)))
        out: Dict[str, Any] = {}
        for i, name in enumerate(self._names):
            if self._swapper is not None:
                shape, dtype = self._swapper.swapper.meta(
                    self._swapper._key(i, "master"))
                out[name] = {k: np.empty(shape, dtype) for k in names}
            else:
                out[name] = {"master": self.master[name],
                             **self.moments[name]}
        return out

    def state_dict(self) -> Dict[str, Any]:
        if self._swapper is not None:
            state = {}
            for i, name in enumerate(self._names):
                back = self._swapper.swap_in_group(i, self._swap_names)
                state[name] = dict(back)
            return {"step": self.step_count, "state": state}
        return {"step": self.step_count,
                "state": {n: {"master": self.master[n], **self.moments[n]}
                          for n in self._names}}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.step_count = int(sd["step"])
        for i, name in enumerate(self._names):
            entry = sd["state"][name]
            cur_shape = None
            if self._swapper is None:
                cur_shape = self.master[name].shape
            if cur_shape is not None and \
                    tuple(np.shape(entry["master"])) != tuple(cur_shape):
                raise ValueError(
                    f"offload checkpoint layout mismatch for {name!r}: "
                    f"saved master shape {np.shape(entry['master'])} vs "
                    f"current {tuple(cur_shape)} — the checkpoint was "
                    "written under a different shard layout (dense vs "
                    "shard-local); re-init with the matching "
                    "process topology / DSTPU_FORCE_SHARD_OFFLOAD setting")
            if self._swapper is not None:
                self._swapper.swap_out_group(i, {k: np.asarray(v)
                                                 for k, v in entry.items()})
            else:
                self.master[name] = np.asarray(entry["master"], np.float32)
                self.moments[name] = {k: np.asarray(v, np.float32)
                                      for k, v in entry.items() if k != "master"}


def lr_f(lr) -> float:
    return float(np.asarray(lr))


def _flatten_with_paths(tree) -> Dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}
