"""ZeRO config — analog of reference ``deepspeed/runtime/zero/config.py``.

Same JSON schema (``zero_optimization`` section). Knobs that only make sense
for the reference's Python-driven scheduling (bucket sizes, overlap_comm,
prefetch counts) are accepted and recorded — on TPU those behaviours are
decided by the XLA scheduler — so existing configs load without edits; the
semantically meaningful fields are ``stage``, ``offload_param``,
``offload_optimizer`` and the consolidation/gather options.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class ZeroStageEnum(int, Enum):
    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = int(1e8)
    max_in_cpu: int = int(1e9)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: ZeroStageEnum = ZeroStageEnum.disabled
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = int(5e8)
    allgather_partitions: bool = True
    allgather_bucket_size: int = int(5e8)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = int(1e9)
    cpu_offload_param: Optional[bool] = None  # deprecated spellings accepted
    cpu_offload_use_pin_memory: Optional[bool] = None
    cpu_offload: Optional[bool] = None
    prefetch_bucket_size: int = Field(int(5e7), alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(int(1e5), alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(int(1e14), alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(int(1e9), alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(int(1e9), alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(
        False, alias="stage3_gather_16bit_weights_on_model_save")
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = 1
    memory_efficient_linear: bool = True

    def __init__(self, **data):
        super().__init__(**data)
        # legacy cpu_offload flags fold into the typed offload configs
        if self.cpu_offload and self.offload_optimizer is None:
            self.offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig(device="cpu")
        if self.cpu_offload_param and self.offload_param is None:
            self.offload_param = DeepSpeedZeroOffloadParamConfig(device="cpu")
