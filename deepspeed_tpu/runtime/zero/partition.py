"""ZeRO partition planning — the TPU-native core of ZeRO stages 1/2/3.

This module replaces ~6,900 LoC of the reference's Python-driven machinery —
``runtime/zero/stage_1_and_2.py`` (DeepSpeedZeroOptimizer, :90),
``runtime/zero/stage3.py`` (DeepSpeedZeroOptimizer_Stage3, :65),
``runtime/zero/partition_parameters.py`` (zero.Init, :601) and
``runtime/zero/partitioned_param_coordinator.py`` (fetch/prefetch/release) —
with a declarative *partition plan*: a pytree of ``PartitionSpec``s per
parameter that tells XLA where every tensor lives, letting the compiler
schedule the collectives the reference drives by hand.

Mapping (see SURVEY.md §2.2):

  stage 0  master params replicated; grads all-reduced (``psum`` over the
           batch axes — the DP fallback path, engine.py:2251).
  stage 1  fp32 master params + optimizer moments sharded over 'data';
           grads replicated (all-reduce); the optimizer update runs on the
           local shard and XLA all-gathers updated params — exactly the
           reference's allgather-after-step (stage_1_and_2.py step:1636).
  stage 2  as stage 1, but the grad pytree carries a sharded constraint so
           the backward pass lowers to ``reduce_scatter`` instead of
           all-reduce (average_tensor, stage_1_and_2.py:894).
  stage 3  compute (bf16) params are *also* sharded: every use triggers an
           XLA-scheduled all-gather which is freed after use — the compiler
           plays the PartitionedParameterCoordinator's prefetch/release role
           with overlap for free. Small params stay replicated below
           ``param_persistence_threshold`` (mirroring persistent params,
           partition_parameters.py).

Tensor parallelism composes orthogonally: logical-axis rules assign 'model'
to hidden dimensions first; ZeRO then shards the largest remaining dimension
over 'data'. Offload (ZeRO-Offload/Infinity host residency) is handled in
``offload.py`` by placing master/optimizer leaves in host memory.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    MeshTopology,
)

# Default logical-axis → mesh-axis rules (model zoo annotates params with
# logical names; anything unmapped is replicated on that dim).
DEFAULT_LOGICAL_RULES: Dict[str, Optional[str]] = {
    "embed": None,            # vocab dim of embeddings — could map to 'model'
    "vocab": MODEL_AXIS,      # output head vocab dim is TP-sharded
    "hidden": None,
    "heads": MODEL_AXIS,      # attention heads / qkv fused dim
    "kv_heads": MODEL_AXIS,   # GQA kv projection dim (must divide by tp)
    "kv": None,
    "mlp": MODEL_AXIS,        # ffn intermediate dim
    "expert": EXPERT_AXIS,    # leading expert dim of MoE params
    "pipe_stage": PIPE_AXIS,  # leading stage dim of pipelined body params
    "seq": None,
    "norm": None,
}


@dataclasses.dataclass
class PartitionPlan:
    """Computes master/compute/grad shardings for every parameter."""

    topology: MeshTopology
    zero_stage: int = 0
    param_persistence_threshold: int = int(1e5)
    logical_rules: Dict[str, Optional[str]] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_LOGICAL_RULES))
    # shard expert params' data-parallel dim over 'data' only (their grads are
    # averaged over 'data', not ('data','expert') — groups._get_expert_data_parallel_group)
    zero_shard_axis: str = DATA_AXIS

    # ------------------------------------------------------------------ specs
    def _tp_spec(self, shape: Tuple[int, ...], logical_axes: Optional[Tuple[str, ...]]):
        """Mesh-axis assignment from logical names (TP/EP dims)."""
        entries: list = [None] * len(shape)
        if logical_axes is None:
            return entries
        assert len(logical_axes) == len(shape), (
            f"logical axes {logical_axes} rank != shape {shape}")
        mesh = self.topology
        for i, name in enumerate(logical_axes):
            axis = self.logical_rules.get(name)
            if axis and mesh.get_dim(axis) > 1 and shape[i] % mesh.get_dim(axis) == 0:
                entries[i] = axis
        return entries

    def _add_zero_axis(self, entries: list, shape: Tuple[int, ...]) -> list:
        """Shard the largest free dim over the data axis (ZeRO partitioning)."""
        dp = self.topology.get_dim(self.zero_shard_axis)
        if dp <= 1:
            return entries
        mesh = self.topology
        # candidate dims: unassigned, divisible by dp; pick the largest
        best, best_size = -1, 0
        for i, (e, s) in enumerate(zip(entries, shape)):
            if e is None and s % dp == 0 and s >= best_size and s > 1:
                best, best_size = i, s
        if best >= 0:
            entries = list(entries)
            entries[best] = self.zero_shard_axis
            return entries
        # try stacking onto an existing TP axis: (model, data) on one dim
        for i, (e, s) in enumerate(zip(entries, shape)):
            if e is not None and not isinstance(e, tuple):
                combined = mesh.get_dim(e) * dp
                if s % combined == 0:
                    entries = list(entries)
                    entries[i] = (e, self.zero_shard_axis)
                    return entries
        return entries  # small/odd-shaped params stay replicated

    def master_spec(self, shape: Tuple[int, ...],
                    logical_axes: Optional[Tuple[str, ...]] = None) -> P:
        """Sharding of fp32 master params and optimizer moments."""
        entries = self._tp_spec(shape, logical_axes)
        if self.zero_stage >= 1:
            entries = self._add_zero_axis(entries, shape)
        return P(*entries)

    def compute_spec(self, shape: Tuple[int, ...],
                     logical_axes: Optional[Tuple[str, ...]] = None) -> P:
        """Sharding of the compute-dtype (bf16) params used in fwd/bwd."""
        entries = self._tp_spec(shape, logical_axes)
        numel = int(np.prod(shape)) if shape else 1
        if self.zero_stage >= 3 and numel >= self.param_persistence_threshold:
            entries = self._add_zero_axis(entries, shape)
        return P(*entries)

    def grad_spec(self, shape: Tuple[int, ...],
                  logical_axes: Optional[Tuple[str, ...]] = None) -> P:
        """Sharding constraint on gradients: sharded from stage 2 up so the
        backward pass lowers to reduce-scatter."""
        entries = self._tp_spec(shape, logical_axes)
        if self.zero_stage >= 2:
            entries = self._add_zero_axis(entries, shape)
        return P(*entries)

    # ------------------------------------------------------------------ trees
    def _tree_specs(self, params, logical_axes_tree, fn):
        if logical_axes_tree is None:
            return jax.tree_util.tree_map(lambda p: fn(tuple(p.shape), None), params)
        return jax.tree_util.tree_map(
            lambda p, ax: fn(tuple(p.shape), tuple(ax) if ax is not None else None),
            params, logical_axes_tree,
            is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
                isinstance(e, (str, type(None))) for e in x)))

    def master_specs(self, params, logical_axes_tree=None):
        return self._tree_specs(params, logical_axes_tree, self.master_spec)

    def compute_specs(self, params, logical_axes_tree=None):
        return self._tree_specs(params, logical_axes_tree, self.compute_spec)

    def grad_specs(self, params, logical_axes_tree=None):
        return self._tree_specs(params, logical_axes_tree, self.grad_spec)

    def shardings(self, specs, memory_kind: Optional[str] = None):
        mesh = self.topology.mesh
        def mk(spec):
            if memory_kind is not None:
                try:
                    return NamedSharding(mesh, spec, memory_kind=memory_kind)
                except (ValueError, TypeError):
                    pass  # backend without memory-kind support (CPU tests)
            return NamedSharding(mesh, spec)
        return jax.tree_util.tree_map(mk, specs, is_leaf=lambda x: isinstance(x, P))

    # -------------------------------------------------------------- batch spec
    def batch_spec(self, ndim: int) -> P:
        """Batch arrays: dim0 over the dense batch axes, dim1 ('seq') when
        sequence parallelism is on."""
        entries: list = [None] * ndim
        entries[0] = (DATA_AXIS, EXPERT_AXIS)
        if ndim >= 2 and self.topology.get_dim(SEQ_AXIS) > 1:
            entries[1] = SEQ_AXIS
        return P(*entries)

    def batch_shardings(self, batch):
        mesh = self.topology.mesh
        return jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, self.batch_spec(getattr(x, "ndim", 0))), batch)
