"""Memory-tiled linear algebra (ZeRO tiling analog).

Reference: ``runtime/zero/tiling.py TiledLinear`` (296 LoC) splits a big
Linear into a tile grid so no full-size activation/weight intermediate
ever exists, and ``runtime/zero/linear.py`` re-implements Linear's autograd
to save memory.  On TPU the second is simply ``jax.checkpoint``; the first
maps to ``lax.scan`` over weight tiles — XLA then allocates tile-sized
intermediates instead of the full output/weight, trading FLOP-pipeline
efficiency for peak-memory, exactly the reference's trade.

The highest-value instance is the LM head: ``chunked_cross_entropy``
computes softmax-CE against a [V, D] embedding without materializing the
[B, T, V] logits (the dominant activation for 50k+ vocabularies) by
scanning sequence chunks.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def tiled_linear(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None,
                 *, out_tiles: int = 1, in_tiles: int = 1) -> jax.Array:
    """y = x @ w (+ b) with the contraction and/or output dim processed in
    tiles (reference TiledLinear's tile grid, as scans).

    x: [..., K], w: [K, N] → [..., N].  ``in_tiles`` must divide K,
    ``out_tiles`` must divide N.
    """
    k, n = w.shape
    assert k % in_tiles == 0, (k, in_tiles)
    assert n % out_tiles == 0, (n, out_tiles)
    kt, nt = k // in_tiles, n // out_tiles

    def out_tile(j):
        wj = jax.lax.dynamic_slice_in_dim(w, j * nt, nt, axis=1)
        if in_tiles == 1:
            return x @ wj.astype(x.dtype)

        def in_step(acc, i):
            xi = jax.lax.dynamic_slice_in_dim(x, i * kt, kt, axis=-1)
            wij = jax.lax.dynamic_slice_in_dim(wj, i * kt, kt, axis=0)
            part = jnp.matmul(xi, wij.astype(x.dtype),
                              preferred_element_type=jnp.float32)
            return acc + part, None

        # accumulate across tiles in fp32 — a dense matmul accumulates in
        # fp32 on the MXU, and per-tile bf16 rounding would drift
        acc0 = jnp.zeros(x.shape[:-1] + (nt,), jnp.float32)
        acc, _ = jax.lax.scan(in_step, acc0, jnp.arange(in_tiles))
        return acc.astype(x.dtype)

    if out_tiles == 1:
        y = out_tile(0)
    else:
        _, tiles = jax.lax.scan(lambda c, j: (c, out_tile(j)), None,
                                jnp.arange(out_tiles))
        # [out_tiles, ..., nt] → [..., n]
        y = jnp.moveaxis(tiles, 0, -2).reshape(x.shape[:-1] + (n,))
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


def chunked_cross_entropy(hidden: jax.Array, embed: jax.Array,
                          labels: jax.Array, *, chunk: int = 128,
                          ignore_index: int = -100
                          ) -> Tuple[jax.Array, jax.Array]:
    """Tied-LM-head softmax cross-entropy without [B, T, V] logits.

    hidden: [B, T, D]; embed: [V, D] (tied embedding); labels: [B, T].
    Scans T in ``chunk``-sized slices: peak logit memory is B*chunk*V.
    A non-divisible tail (e.g. under curriculum-truncated seqlens) is
    processed as one smaller chunk — the memory bound still holds.
    Returns (mean loss over scored tokens, scored-token count) matching
    models/base.cross_entropy_loss semantics (label==ignore_index skipped).
    """
    b, t, d = hidden.shape
    chunk = min(chunk, t)

    def piece(h, lab):
        logits = jnp.einsum("bcd,vd->bcv", h,
                            embed.astype(h.dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return jnp.sum(jnp.where(valid, nll, 0.0)), jnp.sum(valid)

    steps = t // chunk
    main_t = steps * chunk
    hs = hidden[:, :main_t].reshape(b, steps, chunk, d).swapaxes(0, 1)
    ls = labels[:, :main_t].reshape(b, steps, chunk).swapaxes(0, 1)

    def step(carry, sl):
        loss_sum, count = carry
        ps, pc = piece(*sl)
        return (loss_sum + ps, count + pc), None

    (loss_sum, count), _ = jax.lax.scan(
        step, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (hs, ls))
    if main_t < t:                                    # tail chunk
        ps, pc = piece(hidden[:, main_t:], labels[:, main_t:])
        loss_sum, count = loss_sum + ps, count + pc
    count = jnp.maximum(count, 1)   # match base.cross_entropy_loss exactly
    return loss_sum / count, count
