from .config import (
    DeepSpeedZeroConfig,
    DeepSpeedZeroOffloadOptimizerConfig,
    DeepSpeedZeroOffloadParamConfig,
    ZeroStageEnum,
)
from .partition import PartitionPlan


class Init:
    """API-parity shim for ``deepspeed.zero.Init`` (reference
    partition_parameters.py:601). In JAX, parameters are created already
    sharded by jitting ``model.init`` with the plan's out_shardings (see
    DeepSpeedEngine._init_state), so this context manager is a no-op provided
    for source compatibility."""

    def __init__(self, *args, **kwargs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
