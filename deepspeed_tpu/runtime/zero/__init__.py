from .config import (
    DeepSpeedZeroConfig,
    DeepSpeedZeroOffloadOptimizerConfig,
    DeepSpeedZeroOffloadParamConfig,
    ZeroStageEnum,
)
from .partition import PartitionPlan
from .tiling import chunked_cross_entropy, tiled_linear


class Init:
    """API-parity shim for ``deepspeed.zero.Init`` (reference
    partition_parameters.py:601). In JAX, parameters are created already
    sharded by jitting ``model.init`` with the plan's out_shardings (see
    DeepSpeedEngine._init_state), so this context manager is a no-op provided
    for source compatibility."""

    def __init__(self, *args, **kwargs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class GatheredParameters:
    """API-parity shim for ``deepspeed.zero.GatheredParameters``
    (reference partition_parameters.py:1500). ZeRO-3 sharded params here
    are ordinary global ``jax.Array``s — any read already sees the full
    logical value and writes happen functionally through the engine — so
    gathering is a no-op; the context exists for source compatibility."""

    def __init__(self, params=None, modifier_rank=None, *args, **kwargs):
        self.params = params

    def __enter__(self):
        return self.params

    def __exit__(self, *exc):
        return False
