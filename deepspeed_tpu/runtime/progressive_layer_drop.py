"""Progressive Layer Drop (reference
``deepspeed/runtime/progressive_layer_drop.py``): anneal a global keep
probability theta(t) from 1 toward ``theta`` with exponential schedule, and
distribute per-layer keep probabilities so deeper layers drop more —
stochastic depth that accelerates pretraining.

Usage: the engine updates the schedule each step
(``update_state(global_step)``); models consume ``layer_keep_probs`` to
gate each scanned block: x_{l+1} = x_l + keep_l/E[keep_l] * block(x_l)
during training (identity at eval).
"""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self) -> Dict:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        """theta(t) = (1 - theta_bar) * exp(-gamma t) + theta_bar
        (reference update_state)."""

        def _prob(x, gamma, p):
            return (1.0 - p) * math.exp(-gamma * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
        return self.current_theta


def layer_keep_probs(num_layers: int, theta: float) -> np.ndarray:
    """Per-layer keep probability: linear from 1 (first layer) to theta
    (last), the PLD paper's depth schedule."""
    if num_layers == 1:
        return np.array([theta])
    frac = np.arange(num_layers) / (num_layers - 1)
    return 1.0 - frac * (1.0 - theta)


def sample_layer_mask(rng, num_layers: int, theta: float):
    """Bernoulli keep mask [L] plus the inverse-prob scale used when a layer
    IS kept (expectation-preserving residual scaling)."""
    probs = jnp.asarray(layer_keep_probs(num_layers, theta), jnp.float32)
    keep = jax.random.bernoulli(rng, probs)
    return keep, probs
