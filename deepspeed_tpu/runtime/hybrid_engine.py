"""Hybrid engine — RLHF training + generation on shared weights.

Reference analog: ``DeepSpeedHybridEngine`` (runtime/hybrid_engine.py:32):
one engine that trains (actor update) and generates (experience collection)
with the SAME parameters — the reference flips modules between ZeRO-3
training mode and kernel-injected inference containers, (un)fusing LoRA
adapters in place and managing a shared KV workspace (generate:168,
_zero3_forward:333).

TPU-native shape: there is nothing to flip.  Training state and the decode
loop live on the same mesh; ``generate()`` casts the current fp32 masters to
the compute dtype, functionally fuses any LoRA adapters (no in-place
surgery — unfuse is a no-op because the originals are never mutated), and
feeds them to the jitted prefill+decode program reused from the inference
engine.  Weight updates between calls change only the param *values*, so
the compiled generate function is reused without retracing.

LoRA convention: a param subtree {"w"|"kernel"|"weight": W [in,out],
"lora_a": A [in,r], "lora_b": B [r,out], optional "lora_alpha": scalar}
fuses to W + (alpha/r)·(A @ B).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist

_WEIGHT_KEYS = ("w", "kernel", "weight")


def _is_lora_node(node) -> bool:
    return isinstance(node, dict) and "lora_a" in node and "lora_b" in node \
        and any(k in node for k in _WEIGHT_KEYS)


def fuse_lora(params):
    """W + (alpha/r)·A@B for every LoRA node (reference fuse_lora_weight);
    pure — the input tree is untouched."""

    def walk(node):
        if _is_lora_node(node):
            out = dict(node)
            wkey = next(k for k in _WEIGHT_KEYS if k in node)
            a, b = node["lora_a"], node["lora_b"]
            r = a.shape[-1]
            alpha = node.get("lora_alpha", jnp.asarray(float(r)))
            delta = (alpha / r) * (a.astype(jnp.float32) @ b.astype(jnp.float32))
            out[wkey] = (node[wkey].astype(jnp.float32) + delta).astype(
                node[wkey].dtype)
            return out
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)


def unfuse_lora(params, original_params):
    """API parity with the reference's unfuse step: functional fusion never
    mutated the originals, so unfuse just returns them."""
    return original_params


class DeepSpeedHybridEngine(DeepSpeedEngine):
    def __init__(self, model, config, **kw):
        super().__init__(model, config, **kw)
        self._he_cfg = self.config.hybrid_engine
        self._inference_engine = None
        self._has_lora = self._detect_lora()
        # generation bookkeeping (reference latency counters,
        # hybrid_engine.py _t0/_total_latency)
        self.generate_calls = 0
        self.generate_latency_s = 0.0
        self.generated_tokens = 0
        if self._has_lora:
            log_dist("hybrid engine: LoRA adapters detected — fused "
                     "functionally per generate() call", ranks=[0])

    def _detect_lora(self) -> bool:
        def walk(node) -> bool:
            if _is_lora_node(node):
                return True
            if isinstance(node, dict):
                return any(walk(v) for v in node.values())
            return False

        return walk(self.state.params) if isinstance(self.state.params, dict) \
            else False

    # ---------------------------------------------------------------- engine
    def _generation_topology(self):
        """Per-generation TP resize (reference hybrid_engine.py:168
        inference_tp_size): when the configured generation TP differs from
        the training mesh's, build a second mesh over the SAME devices with
        model-axis = inference_tp_size (remaining ways go to data). Params
        are resharded into it on every weight refresh."""
        tp = self._he_cfg.inference_tp_size
        if tp == self.topology.model_parallel_size:
            return self.topology
        from deepspeed_tpu.parallel.topology import build_topology

        devices = list(self.topology.mesh.devices.flat)
        return build_topology(world_size=len(devices), tp=tp, devices=devices)

    def _inference(self):
        if self._inference_engine is None:
            from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
            from deepspeed_tpu.inference.engine import InferenceEngine
            from deepspeed_tpu.utils import groups as groups_mod

            dtype = {"float16": "fp16", "bfloat16": "bf16"}.get(
                self.compute_dtype.__name__, "fp32")
            cfg = DeepSpeedInferenceConfig(
                dtype=dtype,
                max_out_tokens=self._he_cfg.max_out_tokens,
                tensor_parallel={"tp_size": self._he_cfg.inference_tp_size},
            )
            self._inference_engine = InferenceEngine(
                self.module, cfg, params=self._eval_params(),
                topology=self._generation_topology())
            # InferenceEngine.__init__ re-points the global topology at the
            # generation mesh; training collectives must keep seeing theirs
            groups_mod.initialize(self.topology)
        return self._inference_engine

    def _cast_params(self):
        """Current weights in compute dtype, LoRA adapters still separate."""
        params = self.state.params
        if getattr(self, "_host_opt", None) is None:
            params = jax.tree_util.tree_map(
                lambda p: p.astype(self.compute_dtype)
                if p.dtype == jnp.float32 else p, params)
        return params

    def _eval_params(self):
        """Current weights for generation: compute dtype + LoRA fused."""
        params = self._cast_params()
        if self._has_lora:
            params = fuse_lora(params)
        return params

    # -------------------------------------------------------------- generate
    def generate(self, input_ids, **kwargs):
        """Experience-collection generation on the live training weights
        (reference generate:168)."""
        t0 = time.perf_counter()
        inf = self._inference()
        # refresh weights; the compiled decode fn is reused (values change,
        # not shapes). Only a resized generation mesh needs an explicit
        # reshard — same-topology refreshes assign directly and let the
        # compiled program place them at dispatch.
        params = self._eval_params()
        resized = inf.topology is not self.topology
        inf.params = inf._shard_and_cast(params) if resized else params
        # generate() traces lazily: any decode path that consults the global
        # topology at trace time (attn_impl ring/ring_flash/ulysses reads
        # groups.get_mesh()) must capture the GENERATION mesh while the
        # params live on it — swap it in around the call (ADVICE r2)
        if resized:
            from deepspeed_tpu.utils import groups as groups_mod

            groups_mod.initialize(inf.topology)
            try:
                out = inf.generate(input_ids, **kwargs)
            finally:
                groups_mod.initialize(self.topology)
        else:
            out = inf.generate(input_ids, **kwargs)
        self.generate_calls += 1
        self.generate_latency_s += time.perf_counter() - t0
        self.generated_tokens += out.shape[0] * (
            out.shape[1] - np.asarray(input_ids).shape[1])
        if self._he_cfg.release_inference_cache:
            # drop compiled decode programs + their cache buffers (reference
            # release_inference_cache / retake_inference_cache)
            inf._compiled.clear()
        return out

    def eval(self):
        return self

    def train(self, mode: bool = True):
        return self

    def generate_stats(self) -> Dict[str, Any]:
        return {"calls": self.generate_calls,
                "latency_s": self.generate_latency_s,
                "tokens": self.generated_tokens,
                "tokens_per_sec": self.generated_tokens /
                self.generate_latency_s if self.generate_latency_s else 0.0}
