"""TP checkpoint split/merge — import/export Megatron-style sharded
checkpoints.

Reference analog: ``deepspeed/runtime/state_dict_factory.py:427`` (SDLoader
split/merge for loading a checkpoint saved at one model-parallel degree into
another).  This framework's own checkpoints are sharding-agnostic global
arrays (checkpoint_engine), so split/merge exists to interoperate with the
torch ecosystem's per-rank files: merge N tp shards into the global array on
import, split a global array into N shards on export.

Classification (column- vs row-parallel) reuses the AutoTP parser — the
same naming heuristic the reference's MegatronSDLoader hand-codes per
weight type (sd_loader quantize/split logic per attention/mlp name).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from deepspeed_tpu.inference.auto_tp import classify, _bias_kind


def _kind(name: str, ndim: int) -> str:
    b = _bias_kind(name)
    return b if b is not None else classify(name, ndim)


def split_param_for_tp(name: str, array: np.ndarray, tp_size: int,
                       tp_rank: int) -> np.ndarray:
    """One rank's shard of a global param (reference SDLoader.split)."""
    kind = _kind(name, array.ndim)
    axis = {"col": -1, "col-bias": -1, "row": -2}.get(kind)
    if axis is None:
        return array        # replicate
    dim = array.shape[axis]
    if dim % tp_size != 0:  # Megatron-style consumers require equal shards
        raise ValueError(
            f"cannot tp-split '{name}': dim {dim} (axis {axis}) is not "
            f"divisible by tp_size {tp_size} (reference SDLoader asserts "
            f"the same)")
    return np.split(array, tp_size, axis=axis)[tp_rank]


def merge_tp_shards(name: str, shards: Sequence[np.ndarray]) -> np.ndarray:
    """Global array from per-rank shards (reference SDLoader.merge)."""
    if len(shards) == 1:
        return np.asarray(shards[0])
    kind = _kind(name, shards[0].ndim)
    if kind in ("col", "col-bias"):
        return np.concatenate(shards, axis=-1)
    if kind == "row":
        return np.concatenate(shards, axis=-2)
    return np.asarray(shards[0])  # replicated: all shards identical


def split_state_dict(state: Dict[str, np.ndarray], tp_size: int
                     ) -> List[Dict[str, np.ndarray]]:
    """Global flat state dict → tp_size per-rank dicts (export path)."""
    return [{k: split_param_for_tp(k, v, tp_size, r) for k, v in state.items()}
            for r in range(tp_size)]


def merge_state_dicts(shards: Sequence[Dict[str, np.ndarray]]
                      ) -> Dict[str, np.ndarray]:
    """Per-rank dicts → global flat state dict (import path)."""
    keys = shards[0].keys()
    for s in shards[1:]:
        assert s.keys() == keys, "tp shards disagree on parameter names"
    return {k: merge_tp_shards(k, [s[k] for s in shards]) for k in keys}
