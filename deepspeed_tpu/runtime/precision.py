"""Mixed precision: loss scaling + dtype policy.

Analog of reference ``deepspeed/runtime/fp16/loss_scaler.py`` (LossScaler /
DynamicLossScaler, :90) and the bf16/fp16 optimizer wrappers
(``runtime/bf16_optimizer.py``, ``runtime/fp16/fused_optimizer.py``).

On TPU bf16 is native, so the canonical mode is "bf16 compute, fp32 master"
with no loss scaling; fp16 with dynamic scaling is retained for parity. The
scaler state is a jittable pytree so the whole update (overflow check,
scale adjustment, conditional optimizer skip) lives inside the compiled step
— the reference needs a separate allreduce for overflow checks
(runtime/utils.py CheckOverflow); here it is part of the fused program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class LossScalerState(NamedTuple):
    cur_scale: jax.Array          # f32 scalar
    cur_iter: jax.Array           # i32
    last_overflow_iter: jax.Array  # i32
    cur_hysteresis: jax.Array     # i32


@dataclasses.dataclass
class DynamicLossScaler:
    init_scale: float = 2.0 ** 16
    scale_factor: float = 2.0
    scale_window: int = 1000
    min_scale: float = 1.0
    delayed_shift: int = 1  # hysteresis
    consecutive_hysteresis: bool = False

    def init(self) -> LossScalerState:
        return LossScalerState(
            cur_scale=jnp.asarray(self.init_scale, jnp.float32),
            cur_iter=jnp.zeros((), jnp.int32),
            last_overflow_iter=jnp.asarray(-1, jnp.int32),
            cur_hysteresis=jnp.asarray(self.delayed_shift, jnp.int32),
        )

    def update(self, state: LossScalerState, has_overflow: jax.Array) -> LossScalerState:
        def on_overflow(s: LossScalerState) -> LossScalerState:
            new_hyst = s.cur_hysteresis - 1
            drop = new_hyst <= 0
            new_scale = jnp.where(
                drop, jnp.maximum(s.cur_scale / self.scale_factor, self.min_scale), s.cur_scale)
            return LossScalerState(
                cur_scale=new_scale,
                cur_iter=s.cur_iter + 1,
                last_overflow_iter=s.cur_iter,
                cur_hysteresis=jnp.where(drop, jnp.asarray(self.delayed_shift, jnp.int32),
                                         new_hyst).astype(jnp.int32),
            )

        def on_ok(s: LossScalerState) -> LossScalerState:
            grow = (s.cur_iter - s.last_overflow_iter) % self.scale_window == (
                self.scale_window - 1)
            return LossScalerState(
                cur_scale=jnp.where(grow, s.cur_scale * self.scale_factor, s.cur_scale),
                cur_iter=s.cur_iter + 1,
                last_overflow_iter=s.last_overflow_iter,
                cur_hysteresis=s.cur_hysteresis,
            )

        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(has_overflow, a, b), on_overflow(state), on_ok(state))


@dataclasses.dataclass
class StaticLossScaler:
    scale: float = 1.0

    def init(self) -> LossScalerState:
        return LossScalerState(
            cur_scale=jnp.asarray(self.scale, jnp.float32),
            cur_iter=jnp.zeros((), jnp.int32),
            last_overflow_iter=jnp.asarray(-1, jnp.int32),
            cur_hysteresis=jnp.ones((), jnp.int32),
        )

    def update(self, state: LossScalerState, has_overflow: jax.Array) -> LossScalerState:
        return state._replace(cur_iter=state.cur_iter + 1)


def create_loss_scaler(fp16_config) -> Any:
    """Mirror of CREATE_LOSS_SCALER logic (reference fp16/loss_scaler.py)."""
    if fp16_config.loss_scale and fp16_config.loss_scale > 0:
        return StaticLossScaler(scale=float(fp16_config.loss_scale))
    return DynamicLossScaler(
        init_scale=2.0 ** fp16_config.initial_scale_power,
        scale_window=fp16_config.loss_scale_window,
        min_scale=fp16_config.min_loss_scale,
        delayed_shift=fp16_config.hysteresis,
    )


def has_inf_or_nan(tree) -> jax.Array:
    """Global overflow flag for a grad pytree (CheckOverflow analog)."""
    leaves = jax.tree_util.tree_leaves(tree)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(x))) for x in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def global_grad_norm(tree) -> jax.Array:
    """L2 norm over a grad pytree in fp32 (runtime/utils.py get_global_norm)."""
    leaves = jax.tree_util.tree_leaves(tree)
    total = jnp.zeros((), jnp.float32)
    for x in leaves:
        total = total + jnp.sum(jnp.square(x.astype(jnp.float32)))
    return jnp.sqrt(total)


def clip_grads_by_global_norm(tree, max_norm: float, norm: jax.Array = None):
    """clip_grad_norm_ analog (runtime/utils.py:975); returns (clipped, norm)."""
    if norm is None:
        norm = global_grad_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda x: x * scale.astype(x.dtype), tree), norm
