"""FLOPS profiler — analog of reference
``deepspeed/profiling/flops_profiler/profiler.py`` (FlopsProfiler:23,
1294 LoC of module-hook MAC counting).

TPU-native redesign: instead of wrapping every nn.Module method with Python
hooks, the profile comes from XLA itself — ``jax.jit(fn).lower().compile()
.cost_analysis()`` returns the compiler's own flops/bytes estimates for the
WHOLE optimized program (post-fusion, the numbers that actually hit the MXU),
and ``jaxpr`` traversal gives the per-primitive breakdown the reference
reports per-module. This is both cheaper (no per-step overhead at all) and
more truthful than hook-based MAC counting.

API parity: ``FlopsProfiler`` with ``start_profile/stop_profile/
get_total_flops/get_total_params/get_total_duration/print_model_profile``;
``get_model_profile(model, batch)`` one-shot helper (reference
flops_profiler/profiler.py get_model_profile:1103).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np


def _fmt_flops(f: float) -> str:
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(f) < 1000:
            return f"{f:.2f} {unit}FLOPs"
        f /= 1000
    return f"{f:.2f} EFLOPs"


def _fmt_params(n: float) -> str:
    for unit in ("", "k", "M", "B", "T"):
        if abs(n) < 1000:
            return f"{n:.2f} {unit}"
        n /= 1000
    return f"{n:.2f} Q"


def compiled_cost(fn: Callable, *args, static_argnums=()) -> Dict[str, float]:
    """XLA cost analysis of ``jit(fn)(*args)`` — flops / bytes accessed."""
    compiled = jax.jit(fn, static_argnums=static_argnums).lower(*args).compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):  # older jax returns [dict]
            ca = ca[0]
    except Exception:
        ca = {}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "cost_analysis": dict(ca) if ca else {},
    }


def jaxpr_op_breakdown(fn: Callable, *args) -> Dict[str, Dict[str, float]]:
    """Per-primitive flop/count breakdown from the jaxpr (the analog of the
    reference's per-module tree, at primitive granularity)."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    counts: Dict[str, Dict[str, float]] = {}

    def sub_jaxprs(params):
        for v in params.values():
            if hasattr(v, "eqns"):
                yield v
            elif hasattr(v, "jaxpr"):  # ClosedJaxpr
                yield v.jaxpr
            elif isinstance(v, (tuple, list)):
                for item in v:
                    if hasattr(item, "eqns"):
                        yield item
                    elif hasattr(item, "jaxpr"):
                        yield item.jaxpr

    def visit(jxp):
        for eqn in jxp.eqns:
            name = eqn.primitive.name
            entry = counts.setdefault(name, {"count": 0, "flops": 0.0})
            entry["count"] += 1
            entry["flops"] += _eqn_flops(eqn)
            for sub in sub_jaxprs(eqn.params):
                visit(sub)

    visit(jaxpr.jaxpr)
    return counts


def _eqn_flops(eqn) -> float:
    """First-order flop estimate per primitive."""
    name = eqn.primitive.name
    try:
        if name in ("dot_general", "conv_general_dilated"):
            out = eqn.outvars[0].aval
            if name == "dot_general":
                dims = eqn.params["dimension_numbers"]
                lhs = eqn.invars[0].aval
                contract = dims[0][0]
                k = int(np.prod([lhs.shape[i] for i in contract])) if contract else 1
                return 2.0 * float(np.prod(out.shape)) * k
            return 2.0 * float(np.prod(out.shape))
        if name in ("add", "mul", "sub", "div", "max", "min", "exp", "log",
                    "tanh", "rsqrt", "erf", "logistic"):
            return float(np.prod(eqn.outvars[0].aval.shape))
    except Exception:
        pass
    return 0.0


class FlopsProfiler:
    """reference FlopsProfiler:23 API on top of compiled-cost analysis."""

    def __init__(self, model=None, ds_engine=None):
        self.model = model
        self.ds_engine = ds_engine
        self._cost: Dict[str, float] = {}
        self._params: Optional[int] = None
        self._t0: Optional[float] = None
        self._duration = 0.0
        self.started = False

    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.time()

    def stop_profile(self):
        if self._t0 is not None:
            self._duration = time.time() - self._t0
        self.started = False

    def profile_fn(self, fn, *args):
        self._cost = compiled_cost(fn, *args)
        return self._cost

    # ---- totals (reference get_total_* API)
    def get_total_flops(self, as_string: bool = False):
        f = self._cost.get("flops", 0.0)
        return _fmt_flops(f) if as_string else f

    def get_total_duration(self, as_string: bool = False):
        return f"{self._duration:.2f} s" if as_string else self._duration

    def get_total_params(self, as_string: bool = False):
        n = self._params
        if n is None and self.model is not None and hasattr(self.model, "init"):
            shapes = jax.eval_shape(self.model.init, jax.random.PRNGKey(0))
            n = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(shapes))
            self._params = n
        n = n or 0
        return _fmt_params(float(n)) if as_string else n

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        lines = [
            "-------------------------- DeepSpeed-TPU Flops Profiler "
            "--------------------------",
            f"params:                 {self.get_total_params(as_string=True)}",
            f"fwd flops (compiled):   {self.get_total_flops(as_string=True)}",
            f"bytes accessed:         {self._cost.get('bytes_accessed', 0.0):.3e}",
            f"profile duration:       {self.get_total_duration(as_string=True)}",
        ]
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text + "\n")
        else:
            print(text)
        return text

    def end_profile(self):
        self.stop_profile()


def get_model_profile(model, batch, *, rng=None, as_string: bool = True,
                      print_profile: bool = False) -> Tuple[Any, Any, Any]:
    """One-shot (flops, macs, params) like reference get_model_profile:1103.
    ``macs`` is flops/2 by the usual convention."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    params = jax.jit(model.init)(rng)
    prof = FlopsProfiler(model=model)
    prof.start_profile()
    cost = prof.profile_fn(lambda p, b: model.apply(p, b, rngs=None, train=False)[0],
                           params, batch)
    prof.stop_profile()
    if print_profile:
        prof.print_model_profile()
    flops = cost["flops"]
    macs = flops / 2.0
    n_params = prof.get_total_params()
    if as_string:
        return (_fmt_flops(flops), _fmt_params(macs) + "MACs", _fmt_params(float(n_params)))
    return flops, macs, n_params
