from deepspeed_tpu.profiling.config import DeepSpeedFlopsProfilerConfig
from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler,
    compiled_cost,
    get_model_profile,
    jaxpr_op_breakdown,
)

__all__ = ["DeepSpeedFlopsProfilerConfig", "FlopsProfiler", "compiled_cost",
           "get_model_profile", "jaxpr_op_breakdown"]
