"""Flops-profiler config — analog of reference ``deepspeed/profiling/config.py``."""

from __future__ import annotations

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedFlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: str = ""


def get_flops_profiler_config(param_dict: dict) -> DeepSpeedFlopsProfilerConfig:
    return DeepSpeedFlopsProfilerConfig(**param_dict.get("flops_profiler", {}))
