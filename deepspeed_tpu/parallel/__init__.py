from .topology import (
    BATCH_AXES,
    DATA_AXIS,
    EXPERT_AXIS,
    MESH_AXES,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    MeshTopology,
    ParallelDims,
    build_topology,
)

__all__ = [
    "MeshTopology",
    "ParallelDims",
    "build_topology",
    "MESH_AXES",
    "BATCH_AXES",
    "DATA_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "EXPERT_AXIS",
    "SEQ_AXIS",
]
