"""SPMD pipeline executor — the TPU-native pipeline-parallel core.

The reference drives pipelining imperatively: a per-rank instruction stream
(runtime/pipe/schedule.py TrainSchedule:189) interpreted by PipelineEngine
(runtime/pipe/engine.py:40) with NCCL p2p sends between stage processes
(runtime/pipe/p2p.py). On TPU the idiomatic equivalent compiles the WHOLE
schedule into one XLA program: stage weights live on their slice of the
'pipe' mesh axis, microbatches flow stage→stage via ``lax.ppermute`` over
ICI, and the tick loop is a ``lax.scan``. Because ``ppermute`` is
differentiable, ``jax.grad`` of the scanned forward replays the reverse
schedule — backward pipelining without a hand-written 1F1B interpreter
(the bubble profile matches GPipe; the fused scan keeps all stages busy in
steady state exactly like the reference's schedule ticks).

Occupancy semantics (tick t, stage s processes microbatch t-s) are shared
with — and tested against — ``runtime/pipe/schedule.InferenceSchedule``.

``shard_map`` is *manual* only over 'pipe' (``axis_names={'pipe'}``): data /
model / expert / seq axes stay in GSPMD auto mode, so ZeRO sharding and
tensor parallelism compose inside each stage unchanged.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import PIPE_AXIS
from deepspeed_tpu.utils.jax_compat import (has_vma_typing, pcast_varying,
                                            shard_map)


def spmd_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    inputs: jax.Array,
    *,
    mesh,
    num_stages: int,
    num_microbatches: int,
    remat: bool = False,
    index_args: bool = False,
) -> jax.Array:
    """Run ``num_microbatches`` inputs through ``num_stages`` pipeline stages.

    stage_fn(stage_params_slice, x) -> y  — one stage's computation; input and
        output activations must share shape/dtype (stage boundaries of a
        transformer stack satisfy this).
    stage_params — pytree whose leaves have leading dim ``num_stages``,
        sharded ``P('pipe', ...)``.
    inputs — ``[M, ...]`` microbatch stream (replicated over 'pipe').
    index_args — when True, the stage fn is called as
        ``stage_fn(params_slice, x, stage, mb_id)`` with traced int32
        scalars: the stage index and the microbatch index that stage is
        processing this tick (``t - stage``; out-of-range on bubble ticks,
        whose outputs are discarded). Lets callers derive per-(stage,
        microbatch, layer) dropout keys that match the host-driven 1F1B
        interpreter exactly (reference threads CudaRNGStatesTracker state
        through its stages, activation_checkpointing/checkpointing.py:121).

    Returns ``[M, ...]`` last-stage outputs.
    """
    assert inputs.shape[0] == num_microbatches
    S, M = num_stages, num_microbatches
    if not index_args:
        base_fn = stage_fn
        stage_fn = lambda p, x, stage, mb: base_fn(p, x)  # noqa: E731
    if S == 1:
        def body(m, x):
            one = jax.tree_util.tree_map(lambda p: p[0], stage_params)
            return m + 1, stage_fn(one, x, jnp.int32(0), m)
        return jax.lax.scan(body, jnp.int32(0), inputs)[1]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # XLA CPU workaround: the cotangent of an unvarying 16-bit shard_map input
    # lowers to an identity-reduction all-reduce that the CPU AllReducePromotion
    # pass cannot clone ("Invalid binary instruction opcode copy"); carry the
    # stream boundary in f32 there. TPU takes the 16-bit path untouched.
    compute_dtype = inputs.dtype
    f32_boundary = (jax.default_backend() == "cpu" and
                    compute_dtype in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)))
    if f32_boundary:
        inputs = inputs.astype(jnp.float32)

    def run(params_local, xs):
        # per-device view: params leaves [1, ...]; xs is the full [M, ...] stream.
        # Make the stream varying over 'pipe' BEFORE the compute-dtype cast so
        # the transpose's boundary psum runs in the (f32) boundary dtype.
        xs = pcast_varying(xs, (PIPE_AXIS,)).astype(compute_dtype)
        params_one = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(PIPE_AXIS)

        def tick(carry, t):
            state, outputs = carry
            x = jnp.where(stage == 0, xs[t % M], state)
            y = fn(params_one, x, stage, t - stage)
            outputs = outputs.at[(t - (S - 1)) % M].set(y)
            state = jax.lax.ppermute(
                y, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
            return (state, outputs), None

        # carries inherit xs's varying-over-'pipe' type (shard_map VMA typing)
        state0 = jnp.zeros_like(xs[0])
        out0 = jnp.zeros_like(xs)
        (_, outputs), _ = jax.lax.scan(
            tick, (state0, out0), jnp.arange(M + S - 1))
        # [1, M, ...] per device → global [S, M, ...] over 'pipe'
        return outputs[None]

    pipe_in = jax.tree_util.tree_map(lambda _: P(PIPE_AXIS), stage_params)
    outputs = shard_map(
        run, mesh=mesh,
        in_specs=(pipe_in, P()),
        out_specs=P(PIPE_AXIS),
        axis_names={PIPE_AXIS},
        # pre-vma jax cannot type the scan carries' varying-ness (the
        # pcast above is an identity there) — disable its rep checker;
        # vma-typed jax keeps the default strict check
        check_vma=has_vma_typing(),
    )(stage_params, inputs)
    return outputs[-1]  # last stage's buffer


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack S structurally-identical per-stage pytrees on a new leading dim
    (the 'pipe'-sharded dim). Analog of the reference's per-stage module
    partitioning (runtime/pipe/module.py _partition_layers)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)
