"""Device-mesh topology (L3).

TPU-native replacement for the reference's process-group topology machinery
(``deepspeed/utils/groups.py`` and ``deepspeed/runtime/pipe/topology.py``:
ProcessTopology / PipeModelDataParallelTopology / PipelineParallelGrid).

Where the reference builds Cartesian rank→coordinate maps and one
``torch.distributed`` ProcessGroup per axis slice, on TPU all of that collapses
into a single ``jax.sharding.Mesh`` whose named axes ARE the parallel groups:

    axes (outer→inner): ('pipe', 'data', 'expert', 'seq', 'model')

  * 'data'   — ZeRO/data parallelism (reduce-scatter/allgather ride this axis)
  * 'expert' — expert parallelism carved out of the data-parallel world,
               exactly like ``_create_expert_and_data_parallel``
               (reference deepspeed/utils/groups.py:108): dense layers treat
               ('data','expert') jointly as the batch axis, expert weights are
               sharded over 'expert' and dispatched with all_to_all.
  * 'seq'    — sequence/context parallelism (ring attention / Ulysses); absent
               from the reference snapshot (SURVEY §5.7) but first-class here.
  * 'model'  — tensor parallelism; innermost so TP collectives get the
               best ICI locality.
  * 'pipe'   — pipeline stages; outermost so stage boundaries can cross the
               slower links (DCN between slices), matching how the reference
               orders axes (pipe, data, model) in PipeModelDataParallelTopology
               (runtime/pipe/topology.py:244).

Axes of size 1 are always present, so sharding rules never need to special-case
"parallelism disabled".
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"

# THE axis registry: every mesh axis name used as a literal anywhere in
# the tree — P(...), shard_map axis_names, Mesh(...), collective axis
# args — must come from here (machine-enforced by the sharding-contract
# lint pass; register new axes in this tuple, once, with their meaning).
MESH_AXES = (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)

# Axes over which the *batch* dimension is sharded for dense computation.
BATCH_AXES = (DATA_AXIS, EXPERT_AXIS)


@dataclasses.dataclass(frozen=True)
class ParallelDims:
    """Requested parallel degrees. dp = world // (pp*ep*sp*tp) when dp==-1."""

    dp: int = -1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1

    def resolve(self, world_size: int) -> "ParallelDims":
        fixed = self.tp * self.pp * self.ep * self.sp
        dp = self.dp
        if dp in (-1, 0, None):
            assert world_size % fixed == 0, (
                f"world size {world_size} not divisible by tp*pp*ep*sp={fixed}")
            dp = world_size // fixed
        total = dp * fixed
        assert total == world_size, (
            f"dp({dp})*tp({self.tp})*pp({self.pp})*ep({self.ep})*sp({self.sp})"
            f"={total} != world size {world_size}")
        return ParallelDims(dp=dp, tp=self.tp, pp=self.pp, ep=self.ep, sp=self.sp)


class MeshTopology:
    """A named device mesh plus the rank-mapping helpers the reference exposes
    via ProcessTopology (get_coord / get_axis_comm_lists / filter_match)."""

    def __init__(self, dims: ParallelDims, devices: Optional[Sequence] = None):
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        self.dims = dims.resolve(len(devices))
        shape = self.mesh_shape
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
        except Exception:
            dev_array = np.asarray(list(devices)).reshape(shape)
        self.mesh = Mesh(dev_array, MESH_AXES)

    @property
    def mesh_shape(self) -> Tuple[int, ...]:
        d = self.dims
        return (d.pp, d.dp, d.ep, d.sp, d.tp)

    @property
    def world_size(self) -> int:
        return int(np.prod(self.mesh_shape))

    # -- ProcessTopology-compatible helpers (reference runtime/pipe/topology.py:12)
    def get_axis_names(self) -> Tuple[str, ...]:
        return MESH_AXES

    def get_dim(self, axis: str) -> int:
        return dict(zip(MESH_AXES, self.mesh_shape))[axis]

    def get_coord(self, rank: int):
        """rank -> namedtuple of coordinates along each axis."""
        coords = np.unravel_index(rank, self.mesh_shape)
        Coord = collections.namedtuple("Coord", MESH_AXES)
        return Coord(*[int(c) for c in coords])

    def get_rank(self, **coords) -> int:
        full = [coords[a] for a in MESH_AXES]
        return int(np.ravel_multi_index(full, self.mesh_shape))

    def get_rank_repr(self, rank: int, omit_axes=(DATA_AXIS,), inner_sep="_", outer_sep="-") -> str:
        coord = self.get_coord(rank)
        parts = [f"{a}{inner_sep}{getattr(coord, a):02d}"
                 for a in MESH_AXES if a not in omit_axes and self.get_dim(a) > 1]
        return outer_sep.join(parts)

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that communicate along ``axis`` (all other coords equal)."""
        lists = []
        other_axes = [a for a in MESH_AXES if a != axis]
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for combo in itertools.product(*ranges):
            fixed = dict(zip(other_axes, combo))
            group = [self.get_rank(**{axis: i, **fixed}) for i in range(self.get_dim(axis))]
            if len(group) > 1:
                lists.append(group)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        out = []
        for rank in range(self.world_size):
            coord = self.get_coord(rank)
            if all(getattr(coord, k) == v for k, v in filter_kwargs.items()):
                out.append(rank)
        return out

    # ----------------------------------------------------------- degree helpers
    @property
    def data_parallel_size(self) -> int:
        return self.dims.dp * self.dims.ep  # dense batch axis spans both

    @property
    def model_parallel_size(self) -> int:
        return self.dims.tp

    @property
    def pipe_parallel_size(self) -> int:
        return self.dims.pp

    @property
    def expert_parallel_size(self) -> int:
        return self.dims.ep

    @property
    def sequence_parallel_size(self) -> int:
        return self.dims.sp

    def __repr__(self):
        return f"MeshTopology(shape={dict(zip(MESH_AXES, self.mesh_shape))})"


def build_topology(world_size: Optional[int] = None, *, dp: int = -1, tp: int = 1,
                   pp: int = 1, ep: int = 1, sp: int = 1,
                   devices: Optional[Sequence] = None) -> MeshTopology:
    import jax

    if devices is None:
        devices = jax.devices()
    if world_size is not None:
        devices = devices[:world_size]
    return MeshTopology(ParallelDims(dp=dp, tp=tp, pp=pp, ep=ep, sp=sp), devices)
