"""Ring attention + Ulysses sequence parallelism — the long-context core.

The reference snapshot has NO sequence parallelism (SURVEY §5.7): its
long-sequence story is Triton block-sparse attention
(``deepspeed/ops/sparse_attention/``) and curriculum seqlen. The TPU-native
long-context mechanisms are:

  * **Ring attention** (`ring_attention`): q/k/v sharded on the sequence dim
    over the 'seq' mesh axis; K/V blocks rotate around the ICI ring with
    ``ppermute`` while each device accumulates its queries' attention with an
    online (flash-style) softmax. Peak memory per device is O(T/S · T/S) per
    step instead of O(T²); compute overlaps the ring hop. Differentiable
    (the scan + ppermute transpose replays the reverse ring).
  * **Ring + Pallas flash** (`ring_flash_attention`, model
    ``attn_impl="ring_flash"``): same ring, but each hop runs the Pallas
    flash kernel (O(block) VMEM even within a hop) and the backward pass is
    an explicit custom-vjp reverse ring — per-hop ``flash_bwd_parts`` with
    the GLOBAL log-sum-exp (per-hop grads sum exactly), dk/dv accumulators
    riding the ring back to their owners. This is the multi-chip >32k
    long-context path.
  * **Ulysses-style all-to-all** (`ulysses_attention`): the later
    DeepSpeed-Ulysses design — all_to_all swaps the sequence sharding for a
    *head* sharding, runs full-sequence attention for 1/S of the heads
    (Pallas flash kernel by default — O(block) memory over the full T;
    ``inner="dense"`` for the jnp reference), and all_to_alls back.

Both are drop-in replacements for ``multihead_attention`` when the inputs'
sequence dim is sharded over 'seq'.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import SEQ_AXIS
from deepspeed_tpu.utils.jax_compat import (has_vma_typing, pcast_varying,
                                            shard_map)

# true -inf (not finfo.min): fully-masked blocks must zero out in the online
# softmax; the isfinite() guards below depend on it
_NEG_INF = -jnp.inf


def _require_vma(name: str) -> None:
    """Fail FAST on pre-vma jax: these kernels' partial-manual shard_map
    (manual over 'seq' only) wedges the old auto-mode rep machinery inside
    a collective on some backends — a hang-then-SIGABRT is strictly worse
    than a clear error at the call site."""
    if not has_vma_typing():
        raise NotImplementedError(
            f"{name} needs shard_map varying-manual-axes typing "
            f"(jax.lax.pcast; jax {jax.__version__} predates it) — "
            "use attn_impl='dense'/'flash' without sequence parallelism "
            "on this jax")


def ring_attention(
    q: jax.Array,  # [B, T, H, Dh] — T globally sharded over 'seq'
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis: str = SEQ_AXIS,
) -> jax.Array:
    """Blockwise ring attention over the sequence mesh axis."""
    sp = mesh.shape[axis]
    if sp == 1:
        from deepspeed_tpu.ops.attention import multihead_attention

        return multihead_attention(q, k, v, causal=causal, scale=scale)
    _require_vma("ring_attention")
    dh = q.shape[-1]
    sc = scale if scale is not None else dh ** -0.5

    def local(ql, kl, vl):
        # per-device: ql/kl/vl [B, T/S, H, Dh]
        b, t_loc, h, _ = ql.shape
        my = jax.lax.axis_index(axis)
        q_pos = my * t_loc + jnp.arange(t_loc)          # global query positions
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def step(carry, t):
            kl, vl, m, l, o = carry
            # kl currently came from source device (my - t) mod S
            src = (my - t) % sp
            k_pos = src * t_loc + jnp.arange(t_loc)
            s = jnp.einsum("bthd,bshd->bhts", ql, kl).astype(jnp.float32) * sc
            if causal:
                mask = k_pos[None, :] <= q_pos[:, None]  # [Tq, Tk]
                s = jnp.where(mask[None, None], s, _NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1))
            # guard fully-masked rows (exp(-inf - -inf))
            p = jnp.exp(s - m_new[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            corr = jnp.exp(m - m_new)
            corr = jnp.where(jnp.isfinite(m), corr, 0.0)
            l = l * corr + p.sum(axis=-1)
            o = o * corr[..., None] + jnp.einsum(
                "bhts,bshd->bthd", p.astype(vl.dtype), vl).astype(jnp.float32).transpose(0, 2, 1, 3)
            kl = jax.lax.ppermute(kl, axis, perm)
            vl = jax.lax.ppermute(vl, axis, perm)
            return (kl, vl, m_new, l, o), None

        # accumulators become varying over the seq axis after step 1 — mark
        # the initial values accordingly (shard_map VMA typing)
        vary = lambda x: pcast_varying(x, (axis,))
        m0 = vary(jnp.full((b, h, t_loc), _NEG_INF, jnp.float32))
        l0 = vary(jnp.zeros((b, h, t_loc), jnp.float32))
        o0 = vary(jnp.zeros((b, h, t_loc, dh), jnp.float32))
        (_, _, m, l, o), _ = jax.lax.scan(
            step, (kl, vl, m0, l0, o0), jnp.arange(sp))
        out = o / jnp.maximum(l, 1e-20)[..., None]
        return out.transpose(0, 2, 1, 3).astype(ql.dtype)  # [B, T/S, H, Dh]

    spec = P(None, axis)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, axis_names={axis})(q, k, v)


def _merge_parts(lse_a, o_a, lse_b, o_b):
    """Exact merge of two softmax partials given their log-sum-exps:
    o = w_a·o_a + w_b·o_b with w_x = exp(lse_x - logaddexp(lse_a, lse_b)).
    Contract: both partials come from flash_fwd_parts, whose lse is always
    finite (the kernel clamps l >= 1e-20) — fully-masked hops must be
    SKIPPED by the caller (the ring's `live` cond does), not merged."""
    lse_new = jnp.logaddexp(lse_a, lse_b)
    w_a = jnp.exp(lse_a - lse_new)
    w_b = jnp.exp(lse_b - lse_new)
    return lse_new, w_a * o_a.astype(jnp.float32) + w_b * o_b.astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_flash_attention(q, k, v, mesh, causal: bool = True,
                         axis: str = SEQ_AXIS,
                         scale: Optional[float] = None):
    """Ring attention with the Pallas flash kernel per hop.

    Same semantics/sharding contract as ``ring_attention`` ([B, T, H, Dh],
    T sharded over ``axis``), but each ring hop runs the O(block)-VMEM
    flash kernel instead of dense jnp blocks, and the backward pass is an
    explicit reverse ring: per-hop ``flash_bwd_parts`` with the GLOBAL lse
    (so per-hop grads sum exactly), dk/dv accumulators riding the ring back
    to their owners. Hop structure: hop 0 is the causal diagonal (static),
    later hops are all-visible or fully-masked (skipped) by ring position.
    """
    out, _ = _ring_flash_fwd(q, k, v, mesh, causal, axis, scale)
    return out


def _ring_flash_fwd(q, k, v, mesh, causal, axis, scale=None):
    from deepspeed_tpu.ops.flash_attention import flash_fwd_parts

    _require_vma("ring_flash_attention")
    sp = mesh.shape[axis]
    b, h, dh = q.shape[0], q.shape[2], q.shape[3]

    def local(ql, kl, vl):
        # flat [B*H, T/S, Dh] layout for the kernels
        t_loc = ql.shape[1]
        flat = lambda x: x.transpose(0, 2, 1, 3).reshape(-1, x.shape[1], dh)
        qf = flat(ql)
        my = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        # hop 0: own block — causal diagonal (static flag)
        o0, lse0 = flash_fwd_parts(qf, flat(kl), flat(vl), causal=causal,
                                   scale=scale)
        lse_run = lse0.astype(jnp.float32)
        o_run = o0.astype(jnp.float32)
        kl = jax.lax.ppermute(kl, axis, perm)
        vl = jax.lax.ppermute(vl, axis, perm)

        def hop(carry, tstep):
            kl, vl, lse_run, o_run = carry
            src = (my - tstep) % sp
            live = (src < my) if causal else jnp.bool_(True)

            def attend(args):
                kl, vl, lse_run, o_run = args
                o_h, lse_h = flash_fwd_parts(qf, flat(kl), flat(vl),
                                             causal=False, scale=scale)
                lse_new, o_new = _merge_parts(lse_run, o_run,
                                              lse_h.astype(jnp.float32),
                                              o_h.astype(jnp.float32))
                return lse_new, o_new

            lse_run, o_run = jax.lax.cond(
                live, attend, lambda args: (args[2], args[3]),
                (kl, vl, lse_run, o_run))
            kl = jax.lax.ppermute(kl, axis, perm)
            vl = jax.lax.ppermute(vl, axis, perm)
            return (kl, vl, lse_run, o_run), None

        (_, _, lse_run, o_run), _ = jax.lax.scan(
            hop, (kl, vl, lse_run, o_run), jnp.arange(1, sp))
        out = o_run.reshape(b, h, t_loc, dh).transpose(0, 2, 1, 3)
        return out.astype(ql.dtype), lse_run

    spec = P(None, axis)
    check = jax.default_backend() == "tpu" and has_vma_typing()
    out, lse = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, P(None, axis, None)), axis_names={axis},
        check_vma=check)(q, k, v)
    # residuals tagged like flash_attention's, so the save_attn remat
    # policy keeps them and a rematted block never replays the ring
    # (sp kernel launches + 2*sp ppermutes per layer) in backward
    from jax.ad_checkpoint import checkpoint_name

    res = tuple(checkpoint_name(x, "flash_res") for x in (q, k, v, out, lse))
    return out, res


def _ring_flash_bwd(mesh, causal, axis, scale, res, g):
    from deepspeed_tpu.ops.flash_attention import flash_bwd_parts

    q, k, v, out, lse = res
    sp = mesh.shape[axis]
    b, h, dh = q.shape[0], q.shape[2], q.shape[3]

    # delta = rowsum(do * out): elementwise, computed on the sharded arrays
    delta_global = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                           axis=-1)                       # [B, T, H]

    def local2(ql, kl, vl, dol, lsel, deltal):
        t_loc = ql.shape[1]
        flat = lambda x: x.transpose(0, 2, 1, 3).reshape(-1, x.shape[1], dh)
        unflat = lambda x: x.reshape(b, h, t_loc, dh).transpose(0, 2, 1, 3)
        qf, dof = flat(ql), flat(dol)
        deltaf = deltal.transpose(0, 2, 1).reshape(-1, t_loc)[..., None]
        my = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        # hop 0: own block, causal
        dq0, dk0, dv0 = flash_bwd_parts(qf, flat(kl), flat(vl), dof, lsel,
                                        deltaf, causal=causal, scale=scale)
        dq_acc = dq0.astype(jnp.float32)
        dk_acc = dk0.astype(jnp.float32)
        dv_acc = dv0.astype(jnp.float32)
        # k/v and THEIR grad accumulators ride the ring together
        kl = jax.lax.ppermute(kl, axis, perm)
        vl = jax.lax.ppermute(vl, axis, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis, perm)

        def hop(carry, tstep):
            kl, vl, dk_acc, dv_acc, dq_acc = carry
            src = (my - tstep) % sp
            live = (src < my) if causal else jnp.bool_(True)

            def grads(args):
                kl, vl, dk_acc, dv_acc, dq_acc = args
                dq_h, dk_h, dv_h = flash_bwd_parts(
                    qf, flat(kl), flat(vl), dof, lsel, deltaf, causal=False,
                    scale=scale)
                return (dk_acc + dk_h.astype(jnp.float32),
                        dv_acc + dv_h.astype(jnp.float32),
                        dq_acc + dq_h.astype(jnp.float32))

            dk_acc, dv_acc, dq_acc = jax.lax.cond(
                live, grads, lambda args: (args[2], args[3], args[4]),
                (kl, vl, dk_acc, dv_acc, dq_acc))
            kl = jax.lax.ppermute(kl, axis, perm)
            vl = jax.lax.ppermute(vl, axis, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
            return (kl, vl, dk_acc, dv_acc, dq_acc), None

        (kl, vl, dk_acc, dv_acc, dq_acc), _ = jax.lax.scan(
            hop, (kl, vl, dk_acc, dv_acc, dq_acc), jnp.arange(1, sp))
        # after S hops the accumulators are back at their owners
        return (unflat(dq_acc).astype(ql.dtype),
                unflat(dk_acc).astype(kl.dtype),
                unflat(dv_acc).astype(vl.dtype))

    spec = P(None, axis)
    check = jax.default_backend() == "tpu" and has_vma_typing()
    dq, dk, dv = shard_map(
        local2, mesh=mesh,
        in_specs=(spec, spec, spec, spec, P(None, axis, None),
                  P(None, axis, None)),
        out_specs=(spec, spec, spec), axis_names={axis},
        check_vma=check)(q, k, v, g, lse, delta_global)
    return dq, dk, dv


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ulysses_attention(
    q: jax.Array,  # [B, T, H, Dh] — T sharded over 'seq'; H % sp == 0
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis: str = SEQ_AXIS,
    inner: str = "flash",
) -> jax.Array:
    """DeepSpeed-Ulysses-style attention: all_to_all head-scatter, full-
    sequence attention for H/S heads, all_to_all back. The inner attention
    defaults to the Pallas flash kernel (O(block) memory over the FULL
    sequence — measured 36x over dense at seq 8192 single-chip); pass
    ``inner="dense"`` for the jnp reference path."""
    if inner not in ("flash", "dense"):
        raise ValueError(f"ulysses inner must be 'flash' or 'dense', got {inner!r}")
    sp = mesh.shape[axis]

    def attend(qf, kf, vf):
        if inner == "flash":
            from deepspeed_tpu.ops.flash_attention import flash_attention

            return flash_attention(qf, kf, vf, causal, scale)
        from deepspeed_tpu.ops.attention import multihead_attention

        return multihead_attention(qf, kf, vf, causal=causal, scale=scale)

    if sp == 1:
        return attend(q, k, v)
    _require_vma("ulysses_attention")
    assert q.shape[2] % sp == 0, (
        f"ulysses needs heads ({q.shape[2]}) divisible by sp ({sp})")

    def local(ql, kl, vl):
        # [B, T/S, H, Dh] → all_to_all → [B, T, H/S, Dh]
        def scatter(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def gather(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qf, kf, vf = scatter(ql), scatter(kl), scatter(vl)
        return gather(attend(qf, kf, vf))

    spec = P(None, axis)
    # check_vma off only for flash-in-INTERPRET mode: the Pallas interpreter
    # can't type kernel-internal literals against 'seq'-varying refs (jax
    # suggests this exact workaround). Compiled TPU runs keep strict vma
    # checking — that's what flash_attention._sds's vma plumbing is for.
    from deepspeed_tpu.ops.flash_attention import _interpret_default

    strict = (inner != "flash" or not _interpret_default()) and \
        has_vma_typing()
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, axis_names={axis},
                     check_vma=strict)(q, k, v)
