"""Ring attention + Ulysses sequence parallelism — the long-context core.

The reference snapshot has NO sequence parallelism (SURVEY §5.7): its
long-sequence story is Triton block-sparse attention
(``deepspeed/ops/sparse_attention/``) and curriculum seqlen. The TPU-native
long-context mechanisms are:

  * **Ring attention** (`ring_attention`): q/k/v sharded on the sequence dim
    over the 'seq' mesh axis; K/V blocks rotate around the ICI ring with
    ``ppermute`` while each device accumulates its queries' attention with an
    online (flash-style) softmax. Peak memory per device is O(T/S · T/S) per
    step instead of O(T²); compute overlaps the ring hop. Differentiable
    (the scan + ppermute transpose replays the reverse ring).
  * **Ulysses-style all-to-all** (`ulysses_attention`): the later
    DeepSpeed-Ulysses design — all_to_all swaps the sequence sharding for a
    *head* sharding, runs full-sequence attention for 1/S of the heads
    (Pallas flash kernel by default — O(block) memory over the full T;
    ``inner="dense"`` for the jnp reference), and all_to_alls back.

Both are drop-in replacements for ``multihead_attention`` when the inputs'
sequence dim is sharded over 'seq'.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import SEQ_AXIS

# true -inf (not finfo.min): fully-masked blocks must zero out in the online
# softmax; the isfinite() guards below depend on it
_NEG_INF = -jnp.inf


def ring_attention(
    q: jax.Array,  # [B, T, H, Dh] — T globally sharded over 'seq'
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis: str = SEQ_AXIS,
) -> jax.Array:
    """Blockwise ring attention over the sequence mesh axis."""
    sp = mesh.shape[axis]
    if sp == 1:
        from deepspeed_tpu.ops.attention import multihead_attention

        return multihead_attention(q, k, v, causal=causal, scale=scale)
    dh = q.shape[-1]
    sc = scale if scale is not None else dh ** -0.5

    def local(ql, kl, vl):
        # per-device: ql/kl/vl [B, T/S, H, Dh]
        b, t_loc, h, _ = ql.shape
        my = jax.lax.axis_index(axis)
        q_pos = my * t_loc + jnp.arange(t_loc)          # global query positions
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def step(carry, t):
            kl, vl, m, l, o = carry
            # kl currently came from source device (my - t) mod S
            src = (my - t) % sp
            k_pos = src * t_loc + jnp.arange(t_loc)
            s = jnp.einsum("bthd,bshd->bhts", ql, kl).astype(jnp.float32) * sc
            if causal:
                mask = k_pos[None, :] <= q_pos[:, None]  # [Tq, Tk]
                s = jnp.where(mask[None, None], s, _NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1))
            # guard fully-masked rows (exp(-inf - -inf))
            p = jnp.exp(s - m_new[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            corr = jnp.exp(m - m_new)
            corr = jnp.where(jnp.isfinite(m), corr, 0.0)
            l = l * corr + p.sum(axis=-1)
            o = o * corr[..., None] + jnp.einsum(
                "bhts,bshd->bthd", p.astype(vl.dtype), vl).astype(jnp.float32).transpose(0, 2, 1, 3)
            kl = jax.lax.ppermute(kl, axis, perm)
            vl = jax.lax.ppermute(vl, axis, perm)
            return (kl, vl, m_new, l, o), None

        # accumulators become varying over the seq axis after step 1 — mark
        # the initial values accordingly (shard_map VMA typing)
        vary = lambda x: jax.lax.pcast(x, (axis,), to="varying")
        m0 = vary(jnp.full((b, h, t_loc), _NEG_INF, jnp.float32))
        l0 = vary(jnp.zeros((b, h, t_loc), jnp.float32))
        o0 = vary(jnp.zeros((b, h, t_loc, dh), jnp.float32))
        (_, _, m, l, o), _ = jax.lax.scan(
            step, (kl, vl, m0, l0, o0), jnp.arange(sp))
        out = o / jnp.maximum(l, 1e-20)[..., None]
        return out.transpose(0, 2, 1, 3).astype(ql.dtype)  # [B, T/S, H, Dh]

    spec = P(None, axis)
    return jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis})(q, k, v)


def ulysses_attention(
    q: jax.Array,  # [B, T, H, Dh] — T sharded over 'seq'; H % sp == 0
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis: str = SEQ_AXIS,
    inner: str = "flash",
) -> jax.Array:
    """DeepSpeed-Ulysses-style attention: all_to_all head-scatter, full-
    sequence attention for H/S heads, all_to_all back. The inner attention
    defaults to the Pallas flash kernel (O(block) memory over the FULL
    sequence — measured 36x over dense at seq 8192 single-chip); pass
    ``inner="dense"`` for the jnp reference path."""
    if inner not in ("flash", "dense"):
        raise ValueError(f"ulysses inner must be 'flash' or 'dense', got {inner!r}")
    sp = mesh.shape[axis]

    def attend(qf, kf, vf):
        if inner == "flash":
            from deepspeed_tpu.ops.flash_attention import flash_attention

            return flash_attention(qf, kf, vf, causal, scale)
        from deepspeed_tpu.ops.attention import multihead_attention

        return multihead_attention(qf, kf, vf, causal=causal, scale=scale)

    if sp == 1:
        return attend(q, k, v)
    assert q.shape[2] % sp == 0, (
        f"ulysses needs heads ({q.shape[2]}) divisible by sp ({sp})")

    def local(ql, kl, vl):
        # [B, T/S, H, Dh] → all_to_all → [B, T, H/S, Dh]
        def scatter(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def gather(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qf, kf, vf = scatter(ql), scatter(kl), scatter(vl)
        return gather(attend(qf, kf, vf))

    spec = P(None, axis)
    # check_vma off only for flash-in-INTERPRET mode: the Pallas interpreter
    # can't type kernel-internal literals against 'seq'-varying refs (jax
    # suggests this exact workaround). Compiled TPU runs keep strict vma
    # checking — that's what flash_attention._sds's vma plumbing is for.
    from deepspeed_tpu.ops.flash_attention import _interpret_default

    strict = inner != "flash" or not _interpret_default()
    return jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=strict)(q, k, v)
