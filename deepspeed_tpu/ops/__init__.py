from . import registry as _registry_mod
from .registry import OpBuilder, all_ops, get_op_builder, register_op_builder


class _register_all:
    """Importing this module registers the built-in op builders."""


@register_op_builder
class FusedAdamBuilder(_registry_mod.OpBuilder):
    NAME = "fused_adam"

    def load(self):
        from deepspeed_tpu.ops.adam import FusedAdam

        return FusedAdam


@register_op_builder
class CPUAdamBuilder(_registry_mod.OpBuilder):
    NAME = "cpu_adam"

    def load(self):
        from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam

        return DeepSpeedCPUAdam


@register_op_builder
class FusedLambBuilder(_registry_mod.OpBuilder):
    NAME = "fused_lamb"

    def load(self):
        from deepspeed_tpu.ops.adam import FusedLamb

        return FusedLamb


@register_op_builder
class CPUAdagradBuilder(_registry_mod.OpBuilder):
    NAME = "cpu_adagrad"

    def load(self):
        from deepspeed_tpu.ops.adam import DeepSpeedCPUAdagrad

        return DeepSpeedCPUAdagrad


@register_op_builder
class AttentionBuilder(_registry_mod.PallasOpBuilder):
    NAME = "attention"

    def load(self):
        from deepspeed_tpu.ops import attention

        return attention


@register_op_builder
class FlashAttentionBuilder(_registry_mod.PallasOpBuilder):
    NAME = "flash_attention"

    def load(self):
        from deepspeed_tpu.ops import flash_attention

        return flash_attention


@register_op_builder
class RingAttentionBuilder(_registry_mod.PallasOpBuilder):
    NAME = "ring_attention"

    def load(self):
        from deepspeed_tpu.ops import ring_attention

        return ring_attention


def _native_builder_base():
    from deepspeed_tpu.ops.native.builder import NativeOpBuilder

    return NativeOpBuilder


class _NativeBuilderProxy(_registry_mod.OpBuilder):
    """Defer importing the native builder machinery until first use."""

    SOURCES: list = []
    WANT_OPENMP = False
    WANT_SIMD = False

    def _impl(self):
        cached = getattr(self, "_impl_cache", None)
        if cached is None:
            base = _native_builder_base()
            cls = type(self.NAME, (base,), {
                "NAME": self.NAME, "SOURCES": self.SOURCES,
                "WANT_OPENMP": self.WANT_OPENMP, "WANT_SIMD": self.WANT_SIMD,
            })
            cached = self._impl_cache = cls(self.accelerator)
        return cached

    def is_compatible(self, verbose: bool = False) -> bool:
        return self._impl().is_compatible(verbose)

    def compatibility_reason(self) -> str:
        return self._impl().compatibility_reason()

    def load_library(self):
        return self._impl().load_library()


@register_op_builder
class AsyncIOBuilder(_NativeBuilderProxy):
    """Native async file IO engine (reference csrc/aio; op name 'async_io')."""

    NAME = "async_io"
    SOURCES = ["aio/dstpu_aio.cpp"]

    def load(self):
        from deepspeed_tpu.ops import aio

        return aio


@register_op_builder
class SparseAttnBuilder(_registry_mod.PallasOpBuilder):
    """Block-sparse attention (reference ops/sparse_attention Triton kernels
    → LUT-driven Pallas kernel + sparsity config family)."""

    NAME = "sparse_attn"

    def load(self):
        from deepspeed_tpu.ops import sparse_attention

        return sparse_attention


@register_op_builder
class OnebitBuilder(_registry_mod.OpBuilder):
    """1-bit compressed collectives + error-compensated optimizers
    (reference runtime/comm/nccl.py compressed_allreduce + fp16/onebit/)."""

    NAME = "onebit"

    def load(self):
        from deepspeed_tpu.ops import onebit

        return onebit


@register_op_builder
class CPUAdamNativeBuilder(_NativeBuilderProxy):
    """Native vectorized host Adam/Adagrad kernels (reference csrc/adam/
    cpu_adam.cpp); used by the ZeRO-Offload host optimizer step."""

    NAME = "cpu_adam_native"
    SOURCES = ["adam/dstpu_cpu_adam.cpp"]
    WANT_OPENMP = True
    WANT_SIMD = True

    def load(self):
        from deepspeed_tpu.ops import cpu_adam_native

        return cpu_adam_native
