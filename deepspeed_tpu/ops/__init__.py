from . import registry as _registry_mod
from .registry import OpBuilder, all_ops, get_op_builder, register_op_builder


class _register_all:
    """Importing this module registers the built-in op builders."""


@register_op_builder
class FusedAdamBuilder(_registry_mod.OpBuilder):
    NAME = "fused_adam"

    def load(self):
        from deepspeed_tpu.ops.adam import FusedAdam

        return FusedAdam


@register_op_builder
class CPUAdamBuilder(_registry_mod.OpBuilder):
    NAME = "cpu_adam"

    def load(self):
        from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam

        return DeepSpeedCPUAdam


@register_op_builder
class FusedLambBuilder(_registry_mod.OpBuilder):
    NAME = "fused_lamb"

    def load(self):
        from deepspeed_tpu.ops.adam import FusedLamb

        return FusedLamb


@register_op_builder
class CPUAdagradBuilder(_registry_mod.OpBuilder):
    NAME = "cpu_adagrad"

    def load(self):
        from deepspeed_tpu.ops.adam import DeepSpeedCPUAdagrad

        return DeepSpeedCPUAdagrad


@register_op_builder
class AttentionBuilder(_registry_mod.PallasOpBuilder):
    NAME = "attention"

    def load(self):
        from deepspeed_tpu.ops import attention

        return attention


@register_op_builder
class FlashAttentionBuilder(_registry_mod.PallasOpBuilder):
    NAME = "flash_attention"

    def load(self):
        from deepspeed_tpu.ops import flash_attention

        return flash_attention


@register_op_builder
class RingAttentionBuilder(_registry_mod.PallasOpBuilder):
    NAME = "ring_attention"

    def load(self):
        from deepspeed_tpu.ops import ring_attention

        return ring_attention
