"""Python surface over the native C++ async IO engine (csrc/aio/dstpu_aio.cpp).

API parity with the reference's aio op (csrc/aio/py_lib/deepspeed_py_aio_handle.cpp
via ops/op_builder async_io): an ``AsyncIOHandle`` with
``async_pread/async_pwrite/wait`` plus sync variants — operating on numpy
arrays (host memory) instead of torch CPU tensors.  Used by
``runtime/swap_tensor`` for ZeRO-Infinity-style param/optimizer swapping.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB = None


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        from deepspeed_tpu.ops import AsyncIOBuilder

        lib = AsyncIOBuilder().load_library()
        lib.dstpu_aio_create.restype = ctypes.c_void_p
        lib.dstpu_aio_create.argtypes = [ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
        lib.dstpu_aio_destroy.argtypes = [ctypes.c_void_p]
        for fn in (lib.dstpu_aio_pread, lib.dstpu_aio_pwrite):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_uint64, ctypes.c_uint64]
        for fn in (lib.dstpu_aio_sync_pread, lib.dstpu_aio_sync_pwrite):
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_uint64, ctypes.c_uint64]
        lib.dstpu_aio_wait.restype = ctypes.c_int
        lib.dstpu_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.dstpu_aio_wait_all.restype = ctypes.c_int
        lib.dstpu_aio_wait_all.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_block_size.restype = ctypes.c_uint64
        lib.dstpu_aio_block_size.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_queue_depth.restype = ctypes.c_int
        lib.dstpu_aio_queue_depth.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_thread_count.restype = ctypes.c_int
        lib.dstpu_aio_thread_count.argtypes = [ctypes.c_void_p]
        _LIB = lib
    return _LIB


def _as_buffer(arr: np.ndarray):
    assert arr.flags["C_CONTIGUOUS"], "aio requires contiguous buffers"
    return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes


class AsyncIOHandle:
    """Reference ``aio_handle`` analog: pool of IO threads + request queue."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 32,
                 single_submit: bool = False, overlap_events: bool = True,
                 num_threads: int = 8):
        self._lib = _lib()
        self._h = self._lib.dstpu_aio_create(block_size, queue_depth, num_threads)
        if not self._h:
            raise RuntimeError("failed to create aio engine")
        # kept for config parity / ds_report
        self.single_submit = single_submit
        self.overlap_events = overlap_events

    # -- introspection (reference get_block_size/get_queue_depth/...)
    def get_block_size(self) -> int:
        return self._lib.dstpu_aio_block_size(self._h)

    def get_queue_depth(self) -> int:
        return self._lib.dstpu_aio_queue_depth(self._h)

    def get_thread_count(self) -> int:
        return self._lib.dstpu_aio_thread_count(self._h)

    # -- async ops: buffer must stay alive until wait()
    def async_pread(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        ptr, nbytes = _as_buffer(buffer)
        rid = self._lib.dstpu_aio_pread(self._h, os.fsencode(filename), ptr,
                                        nbytes, offset)
        if rid < 0:
            raise OSError(-rid, f"aio pread submit failed for {filename}")
        return rid

    def async_pwrite(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        ptr, nbytes = _as_buffer(buffer)
        rid = self._lib.dstpu_aio_pwrite(self._h, os.fsencode(filename), ptr,
                                         nbytes, offset)
        if rid < 0:
            raise OSError(-rid, f"aio pwrite submit failed for {filename}")
        return rid

    def wait(self, request_id: Optional[int] = None) -> int:
        """Wait for one request (or all inflight when id is None)."""
        if request_id is None:
            rc = self._lib.dstpu_aio_wait_all(self._h)
        else:
            rc = self._lib.dstpu_aio_wait(self._h, request_id)
        if rc < 0:
            raise OSError(-rc, "aio request failed")
        return rc

    # -- sync ops
    def sync_pread(self, buffer: np.ndarray, filename: str, offset: int = 0):
        ptr, nbytes = _as_buffer(buffer)
        rc = self._lib.dstpu_aio_sync_pread(self._h, os.fsencode(filename), ptr,
                                            nbytes, offset)
        if rc < 0:
            raise OSError(-rc, f"aio sync pread failed for {filename}")

    def sync_pwrite(self, buffer: np.ndarray, filename: str, offset: int = 0):
        ptr, nbytes = _as_buffer(buffer)
        rc = self._lib.dstpu_aio_sync_pwrite(self._h, os.fsencode(filename), ptr,
                                             nbytes, offset)
        if rc < 0:
            raise OSError(-rc, f"aio sync pwrite failed for {filename}")

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.dstpu_aio_destroy(self._h)
                self._h = None
        except Exception:
            pass
