"""Fused optimizers.

TPU-native re-design of the reference's native optimizer kernels:
  * FusedAdam      — csrc/adam/multi_tensor_adam.cu (multi-tensor Adam)
  * DeepSpeedCPUAdam — csrc/adam/cpu_adam.cpp (AVX Adam for ZeRO-Offload)
  * FusedLamb      — csrc/lamb/fused_lamb_cuda_kernel.cu
  * cpu_adagrad    — csrc/adagrad/cpu_adagrad.cpp

On TPU "fused" means: the whole-pytree update is one XLA program — tree_map
over leaves compiles into fused elementwise kernels with no per-tensor launch
overhead, which is what multi_tensor_apply bought on CUDA. The CPU variants
are the same math with state placed in host memory (see
``runtime/zero/offload.py``); no hand-written AVX is needed because XLA:CPU
vectorises the same loop.

All optimizers share a functional interface:
    state = opt.init(params)
    new_params, new_state = opt.step(params, grads, state, lr)
Everything is jittable; ``lr`` is a traced scalar so LR schedules never
trigger recompilation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    step: jax.Array
    exp_avg: Any  # pytree like params
    exp_avg_sq: Any


def _tree_zeros_like(params, dtype=None):
    return jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=dtype or p.dtype), params)


@dataclasses.dataclass
class FusedAdam:
    """Adam/AdamW (reference FusedAdam, deepspeed/ops/adam/fused_adam.py:18).

    ``adam_w_mode=True`` gives decoupled weight decay (AdamW), matching the
    reference's default.
    """

    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    adam_w_mode: bool = True
    bias_correction: bool = True
    amsgrad: bool = False
    state_dtype: Any = jnp.float32

    name = "adam"

    def __post_init__(self):
        if self.amsgrad:
            raise ValueError("FusedAdam does not support amsgrad (matches reference)")

    def init(self, params) -> AdamState:
        return AdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=_tree_zeros_like(params, self.state_dtype),
            exp_avg_sq=_tree_zeros_like(params, self.state_dtype),
        )

    def step(self, params, grads, state: AdamState, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        count = state.step + 1
        if self.bias_correction:
            bc1 = 1.0 - b1 ** count.astype(jnp.float32)
            bc2 = 1.0 - b2 ** count.astype(jnp.float32)
        else:
            bc1 = bc2 = 1.0

        def upd(p, g, m, v):
            g = g.astype(m.dtype)
            if self.weight_decay > 0.0 and not self.adam_w_mode:
                # L2 mode folds decay into the gradient before the moments
                g = g + self.weight_decay * p.astype(g.dtype)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * (g * g)
            m_hat = m_new / bc1
            v_hat = v_new / bc2
            update = m_hat / (jnp.sqrt(v_hat) + self.eps)
            if self.weight_decay > 0.0 and self.adam_w_mode:
                update = update + self.weight_decay * p.astype(update.dtype)
            p_new = p.astype(jnp.float32) - lr * update
            return p_new.astype(p.dtype), m_new, v_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        out = [upd(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        new_p = treedef.unflatten([o[0] for o in out])
        new_m = treedef.unflatten([o[1] for o in out])
        new_v = treedef.unflatten([o[2] for o in out])
        return new_p, AdamState(step=count, exp_avg=new_m, exp_avg_sq=new_v)


@dataclasses.dataclass
class DeepSpeedCPUAdam(FusedAdam):
    """Same math as FusedAdam; the engine places its state in host memory when
    ``offload_optimizer.device == "cpu"`` (reference ops/adam/cpu_adam.py:13)."""

    name = "cpu_adam"
    host_state: bool = True


class LambState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_sq: Any


@dataclasses.dataclass
class FusedLamb:
    """LAMB with per-layer trust ratio (reference FusedLamb,
    deepspeed/ops/lamb/fused_lamb.py; kernel csrc/lamb/fused_lamb_cuda_kernel.cu).
    """

    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    max_coeff: float = 10.0
    min_coeff: float = 0.01
    bias_correction: bool = True
    state_dtype: Any = jnp.float32

    name = "lamb"

    def init(self, params) -> LambState:
        return LambState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=_tree_zeros_like(params, self.state_dtype),
            exp_avg_sq=_tree_zeros_like(params, self.state_dtype),
        )

    def step(self, params, grads, state: LambState, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        count = state.step + 1
        bc1 = 1.0 - b1 ** count.astype(jnp.float32) if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** count.astype(jnp.float32) if self.bias_correction else 1.0

        def upd(p, g, m, v):
            g = g.astype(m.dtype)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * (g * g)
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay > 0.0:
                update = update + self.weight_decay * p.astype(update.dtype)
            w_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(update)
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                1.0,
            )
            p_new = p.astype(jnp.float32) - lr * trust * update
            return p_new.astype(p.dtype), m_new, v_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        out = [upd(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        return (treedef.unflatten([o[0] for o in out]),
                LambState(step=count,
                          exp_avg=treedef.unflatten([o[1] for o in out]),
                          exp_avg_sq=treedef.unflatten([o[2] for o in out])))


class AdagradState(NamedTuple):
    step: jax.Array
    sum_sq: Any


@dataclasses.dataclass
class DeepSpeedCPUAdagrad:
    """Adagrad (reference csrc/adagrad/cpu_adagrad.cpp)."""

    lr: float = 1e-2
    eps: float = 1e-10
    weight_decay: float = 0.0
    state_dtype: Any = jnp.float32

    name = "adagrad"
    host_state: bool = True

    def init(self, params) -> AdagradState:
        return AdagradState(step=jnp.zeros((), jnp.int32),
                            sum_sq=_tree_zeros_like(params, self.state_dtype))

    def step(self, params, grads, state: AdagradState, lr=None):
        lr = self.lr if lr is None else lr

        def upd(p, g, s):
            g = g.astype(s.dtype)
            if self.weight_decay > 0.0:
                g = g + self.weight_decay * p.astype(g.dtype)
            s_new = s + g * g
            p_new = p.astype(jnp.float32) - lr * g / (jnp.sqrt(s_new) + self.eps)
            return p_new.astype(p.dtype), s_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(state.sum_sq)
        out = [upd(p, g, s) for p, g, s in zip(flat_p, flat_g, flat_s)]
        return (treedef.unflatten([o[0] for o in out]),
                AdagradState(step=state.step + 1,
                             sum_sq=treedef.unflatten([o[1] for o in out])))


class SGDState(NamedTuple):
    step: jax.Array
    momentum_buf: Any


@dataclasses.dataclass
class SGD:
    lr: float = 1e-2
    momentum: float = 0.0
    weight_decay: float = 0.0
    nesterov: bool = False

    name = "sgd"

    def init(self, params) -> SGDState:
        return SGDState(step=jnp.zeros((), jnp.int32),
                        momentum_buf=_tree_zeros_like(params, jnp.float32))

    def step(self, params, grads, state: SGDState, lr=None):
        lr = self.lr if lr is None else lr

        def upd(p, g, b):
            g = g.astype(jnp.float32)
            if self.weight_decay > 0.0:
                g = g + self.weight_decay * p.astype(jnp.float32)
            b_new = self.momentum * b + g
            d = g + self.momentum * b_new if self.nesterov else b_new
            if self.momentum == 0.0:
                b_new = b
                d = g
            p_new = p.astype(jnp.float32) - lr * d
            return p_new.astype(p.dtype), b_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_b = treedef.flatten_up_to(state.momentum_buf)
        out = [upd(p, g, b) for p, g, b in zip(flat_p, flat_g, flat_b)]
        return (treedef.unflatten([o[0] for o in out]),
                SGDState(step=state.step + 1,
                         momentum_buf=treedef.unflatten([o[1] for o in out])))


def _onebit(name):
    def make(**kw):
        from deepspeed_tpu.ops import onebit

        return getattr(onebit, name)(**kw)
    return make


OPTIMIZER_REGISTRY: Dict[str, Any] = {
    "adam": FusedAdam,
    "adamw": lambda **kw: FusedAdam(adam_w_mode=True, **kw),
    "fusedadam": FusedAdam,
    "cpu_adam": DeepSpeedCPUAdam,
    "deepspeedcpuadam": DeepSpeedCPUAdam,
    "lamb": FusedLamb,
    "fusedlamb": FusedLamb,
    "adagrad": DeepSpeedCPUAdagrad,
    "sgd": SGD,
    # 1-bit error-compensated optimizers (reference runtime/fp16/onebit/)
    "onebitadam": _onebit("OnebitAdam"),
    "onebitlamb": _onebit("OnebitLamb"),
    "zerooneadam": _onebit("ZeroOneAdam"),
}


def build_optimizer(name: str, params_dict: Optional[Dict[str, Any]] = None):
    """Build an optimizer from a DeepSpeed-style config section
    (engine._configure_basic_optimizer analog, reference engine.py:1187)."""
    key = name.lower().replace("_", "").replace("one" + "bit", "onebit")
    table = {k.replace("_", ""): v for k, v in OPTIMIZER_REGISTRY.items()}
    if key not in table:
        raise ValueError(f"Unknown optimizer '{name}'. Known: {sorted(OPTIMIZER_REGISTRY)}")
    kwargs = dict(params_dict or {})
    # accept torch-style names
    if "betas" in kwargs:
        kwargs["betas"] = tuple(kwargs["betas"])
    kwargs.pop("torch_adam", None)
    if key == "adamw":
        kwargs.pop("adam_w_mode", None)
    return table[key](**kwargs)
