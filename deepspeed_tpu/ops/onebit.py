"""1-bit (sign) compressed collectives + error-compensated optimizers.

Reference analogs:
  * ``NcclBackend.compressed_allreduce`` (runtime/comm/nccl.py:54) — the
    error-compensated two-stage sign-compressed allreduce: worker compress →
    alltoall → per-chunk average + server compress → allgather.
  * ``OnebitAdam`` (runtime/fp16/onebit/adam.py:13), ``OnebitLamb``
    (onebit/lamb.py:14), ``ZeroOneAdam`` (onebit/zoadam.py:13) — fp32-exact
    warmup, then the *momentum* is communicated 1-bit-compressed while the
    variance stays frozen (Adam) / the per-layer scaling factor learned in
    warmup is applied frozen (LAMB).

TPU-native shape: the collective runs INSIDE jit under ``shard_map`` over
the data axis — signs travel as int8 over ICI (the reference packs bits via
cupy; on TPU int8 lanes + XLA collective fusion make explicit bit-packing a
pessimization), scales are fp32 scalars per chunk.  Error feedback tensors
are functional optimizer state (per-device distinct — shard them over the
data axis, never replicate).  The warmup↔compressed switch is a
``lax.cond`` so only ONE set of collectives executes per step: exact pmean
during warmup, compressed alltoall/allgather after (``jnp.where`` would pay
both).

Engine note: ``DeepSpeedEngine``'s compiled GSPMD path communicates
gradients exactly (XLA-scheduled), so the engine constructs these with
``with_compression=False`` — exact math, no error-state memory.  The true
1-bit path needs local (per-device, unreduced) grads: run the optimizer
under ``shard_map`` passing ``axis_name`` (see tests/unit/ops/test_onebit.py
for the canonical DP loop).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _ensure_varying(x: jax.Array, axis_name: str) -> jax.Array:
    """Align shard_map's varying-manual-axes type: no-op when already
    varying over ``axis_name``."""
    try:
        vma = jax.typeof(x).vma
    except Exception:
        return x
    if axis_name in vma:
        return x
    from deepspeed_tpu.utils.jax_compat import pcast_varying

    return pcast_varying(x, axis_name)


# ----------------------------------------------------------- core compression
def _sign_compress(c: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """c → (scale, signs∈{-1,+1} int8, error). scale preserves the l1 norm
    (reference: scale = |c|.mean(), signs = c.sign())."""
    scale = jnp.mean(jnp.abs(c))
    signs = jnp.where(c >= 0, jnp.int8(1), jnp.int8(-1))
    error = c - scale * signs.astype(c.dtype)
    return scale, signs, error


def compressed_allreduce(x: jax.Array, worker_error: jax.Array,
                         server_error: jax.Array, axis_name: str):
    """Error-compensated 1-bit mean-allreduce over ``axis_name``.

    Must run under shard_map with ``axis_name`` manual. ``x`` is this
    device's local tensor (1-D); worker/server errors are PER-DEVICE state
    of the same shape (the server error is live only in this device's owned
    chunk, matching the reference's per-rank server_error chunks).

    Returns (averaged tensor, new_worker_error, new_server_error).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    numel = x.shape[0]
    pad = (-numel) % n
    xp = jnp.pad(x + worker_error[:numel], ((0, pad),))
    chunk = xp.shape[0] // n

    # stage 1: worker compression
    scale, signs, werr = _sign_compress(xp)
    # alltoall: device j receives chunk j of every device's signs
    my_chunks_signs = signs.reshape(n, chunk)
    recv_signs = jax.lax.all_to_all(my_chunks_signs, axis_name, split_axis=0,
                                    concat_axis=0, tiled=False)
    recv_scales = jax.lax.all_gather(scale, axis_name)  # [n]
    # average my owned chunk across all senders
    avg_chunk = jnp.mean(recv_scales[:, None] *
                         recv_signs.reshape(n, chunk).astype(x.dtype), axis=0)

    # stage 2: server compression of my owned chunk (+ my server error slice)
    serr_slice = jax.lax.dynamic_slice(
        jnp.pad(server_error, ((0, pad),)), (idx * chunk,), (chunk,))
    s_scale, s_signs, s_err = _sign_compress(avg_chunk + serr_slice)

    # allgather the compressed server chunks → everyone reconstructs the mean
    all_scales = jax.lax.all_gather(s_scale, axis_name)          # [n]
    all_signs = jax.lax.all_gather(s_signs, axis_name)           # [n, chunk]
    out = (all_scales[:, None] * all_signs.astype(x.dtype)).reshape(-1)[:numel]
    # consensus reconstruction may be device-invariant in shard_map's vma
    # typing; mark it varying so it composes with per-device values in
    # lax.cond branches whose other side is varying
    out = _ensure_varying(out, axis_name)

    # scatter my server-error slice back into the full-size carrier
    new_serr = jax.lax.dynamic_update_slice(
        jnp.zeros((numel + pad,), server_error.dtype), s_err,
        (idx * chunk,))[:numel]
    new_werr = werr[:numel]
    return out, new_werr, new_serr


# --------------------------------------------------------------- shared state
class OnebitState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any    # per-device distinct; shard over the data axis
    server_error: Any
    frozen_scale: Any    # per-leaf scalar (LAMB trust ratio frozen at warmup end)


OnebitAdamState = OnebitState  # back-compat alias


@dataclasses.dataclass
class _OnebitBase:
    """Shared step driver: subclasses supply the variance/sync/update policy
    (the 3 ways OnebitAdam / OnebitLamb / ZeroOneAdam differ)."""

    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    freeze_step: int = 100
    with_compression: bool = True  # False: engine/GSPMD exact path, no error state

    name = "onebit_base"

    # ------------------------------------------------------------------ state
    def init(self, params) -> OnebitState:
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        if self.with_compression:
            we, se = zeros(), zeros()
        else:  # exact-comm mode keeps the pytree structure but no memory
            empty = jax.tree_util.tree_map(
                lambda p: jnp.zeros((0,), jnp.float32), params)
            we, se = empty, empty
        return OnebitState(
            step=jnp.zeros((), jnp.int32), exp_avg=zeros(), exp_avg_sq=zeros(),
            worker_error=we, server_error=se,
            frozen_scale=jax.tree_util.tree_map(
                lambda p: jnp.ones((), jnp.float32), params))

    # --------------------------------------------------------------- policies
    def _variance_on(self, count):
        """Does the variance update this step? (Adam/LAMB: warmup only)."""
        return count <= self.freeze_step

    def _sync_on(self, count):
        """Does the compressed sync run this (post-warmup) step?"""
        return jnp.asarray(True)

    def _var_from_momentum(self) -> bool:
        """Variance signal: grads (Adam/LAMB warmup) or synced momentum
        (ZeroOneAdam's schedule)."""
        return False

    def _param_update(self, p, update, lr, warm, fscale):
        return (p.astype(jnp.float32) - lr * update).astype(p.dtype)

    def _new_frozen_scale(self, count, p, update, fscale):
        return fscale

    # ------------------------------------------------------------------- step
    def step(self, params, grads, state: OnebitState, lr=None,
             axis_name: Optional[str] = None):
        """``grads`` are LOCAL when axis_name is set (compression replaces
        the grad allreduce); exact/global otherwise."""
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        count = state.step + 1
        warm = count <= self.freeze_step
        var_on = self._variance_on(count)
        sync_on = self._sync_on(count)

        def leaf_update(p, g, m, v, we, se, fscale):
            g = g.astype(jnp.float32)

            if axis_name is None:
                # exact mode (single device / engine GSPMD path): grads are
                # already global — same math, no collectives
                m_new = b1 * m + (1 - b1) * g
                signal = m_new * m_new if self._var_from_momentum() else g * g
                v_new = jnp.where(var_on, b2 * v + (1 - b2) * signal, v)
                we_new, se_new = we, se
            else:
                # one lax.cond per leaf so exactly ONE set of collectives
                # runs: exact pmean in warmup, compressed sync after
                def warm_branch(operands):
                    m, v, we, se, g = operands
                    ge = jax.lax.pmean(g, axis_name)
                    ev = lambda t: _ensure_varying(t, axis_name)
                    return (ev(b1 * m + (1 - b1) * ge),
                            ev(b2 * v + (1 - b2) * ge * ge), ev(we), ev(se))

                def compressed_branch(operands):
                    m, v, we, se, g = operands
                    m_local = b1 * m + (1 - b1) * g

                    def do_sync(ops):
                        m_local, we, se = ops
                        shape = m_local.shape
                        ms, we2, se2 = compressed_allreduce(
                            m_local.reshape(-1), we.reshape(-1),
                            se.reshape(-1), axis_name)
                        return ms.reshape(shape), we2.reshape(shape), \
                            se2.reshape(shape)

                    def skip_sync(ops):
                        m_local, we, se = ops
                        return m_local, we, se

                    m_sync, we2, se2 = jax.lax.cond(
                        sync_on, do_sync, skip_sync, (m_local, we, se))
                    # variance schedule in the compressed stage uses the
                    # synced momentum as its signal (ZeroOneAdam; Adam/LAMB
                    # have var_on=False here so v stays frozen)
                    v2 = jnp.where(var_on & ~warm,
                                   b2 * v + (1 - b2) * m_sync * m_sync, v)
                    ev = lambda t: _ensure_varying(t, axis_name)
                    return ev(m_sync), ev(v2), ev(we2), ev(se2)

                m_new, v_new, we_new, se_new = jax.lax.cond(
                    warm, warm_branch, compressed_branch, (m, v, we, se, g))

            bc1 = 1 - b1 ** count.astype(jnp.float32)
            bc2 = 1 - b2 ** count.astype(jnp.float32)
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay > 0:
                update = update + self.weight_decay * p.astype(jnp.float32)
            fscale_new = self._new_frozen_scale(count, p, update, fscale)
            p_new = self._param_update(p, update, lr, warm, fscale_new)
            return p_new, m_new, v_new, we_new, se_new, fscale_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        parts = [treedef.flatten_up_to(t) for t in
                 (grads, state.exp_avg, state.exp_avg_sq,
                  state.worker_error, state.server_error, state.frozen_scale)]
        out = [leaf_update(p, *leaves) for p, *leaves in zip(flat_p, *parts)]
        unf = lambda i: treedef.unflatten([o[i] for o in out])
        return unf(0), OnebitState(step=count, exp_avg=unf(1),
                                   exp_avg_sq=unf(2), worker_error=unf(3),
                                   server_error=unf(4), frozen_scale=unf(5))


# ----------------------------------------------------------------- OnebitAdam
@dataclasses.dataclass
class OnebitAdam(_OnebitBase):
    """reference OnebitAdam (runtime/fp16/onebit/adam.py:13): exact Adam for
    ``freeze_step`` warmup steps, then variance freezes and the momentum is
    synchronized with the 1-bit compressed allreduce."""

    name = "onebit_adam"


# ----------------------------------------------------------------- OnebitLamb
@dataclasses.dataclass
class OnebitLamb(_OnebitBase):
    """reference OnebitLamb (onebit/lamb.py:14): live per-layer trust ratio
    during warmup; at the freeze boundary the ratio is FROZEN and applied as
    a fixed per-layer scaling through the compressed stage (norm ratios of
    sign-quantized updates are too noisy to trust live)."""

    max_coeff: float = 10.0
    min_coeff: float = 0.01

    name = "onebit_lamb"

    def _live_trust(self, p, update):
        w_norm = jnp.linalg.norm(p.astype(jnp.float32))
        u_norm = jnp.linalg.norm(update)
        return jnp.where((w_norm > 0) & (u_norm > 0),
                         jnp.clip(w_norm / u_norm, self.min_coeff,
                                  self.max_coeff), 1.0)

    def _new_frozen_scale(self, count, p, update, fscale):
        # track the live ratio until the freeze boundary, then hold
        return jnp.where(count <= self.freeze_step,
                         self._live_trust(p, update), fscale)

    def _param_update(self, p, update, lr, warm, fscale):
        # warmup: live trust ratio; compressed stage: frozen ratio
        return (p.astype(jnp.float32) - lr * fscale * update).astype(p.dtype)


# ----------------------------------------------------------------- ZeroOneAdam
@dataclasses.dataclass
class ZeroOneAdam(_OnebitBase):
    """reference ZeroOneAdam (onebit/zoadam.py:13): 0/1 Adam — variance
    updates on an interval schedule until ``var_freeze_step`` and the
    compressed momentum sync runs on a local-step policy interval (steps
    without sync skip ALL communication — that is the point of 0/1 Adam)."""

    var_freeze_step: int = 100
    var_update_scaler: int = 16
    local_step_scaler: int = 32768
    local_step_clipper: int = 16

    name = "zero_one_adam"

    def __post_init__(self):
        # 0/1 Adam has no warmup/freeze split in the Adam sense: compression
        # starts immediately; freeze_step gates only the variance schedule
        self.freeze_step = 0

    def _variance_on(self, count):
        return ((count <= self.var_freeze_step) &
                (jnp.mod(count, self.var_update_scaler) == 0)) | (count == 1)

    def _sync_on(self, count):
        # clip the EXPONENT before the power: int32 2**31 wraps negative and
        # would silently disable momentum sync for the rest of training
        max_exp = int(np.log2(max(self.local_step_clipper, 1)))
        exp = jnp.minimum(count // jnp.maximum(self.local_step_scaler, 1),
                          max_exp)
        k = jnp.minimum(2 ** exp, self.local_step_clipper)
        return (count <= self.var_freeze_step) | (jnp.mod(count, k) == 0)

    def _var_from_momentum(self) -> bool:
        return True
