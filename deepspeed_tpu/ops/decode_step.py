"""Fused single-token decode step: KV-cache write + attention, one Pallas
invocation per layer, manual double-buffered DMA over the full stacked cache.

Reference counterpart: ``softmax_context`` + the inference_context.h KV
workspace (csrc/transformer/inference/includes/inference_context.h:287 —
the reference's workspace exists precisely to CONTROL the KV layout that
its fused decode kernels stream). Here the same control is exercised
through Pallas: because every access to the decode loop's cache carry is
a Pallas op (this kernel owns both the write and the read), XLA's layout
assignment keeps the carry in the default row-major [L, B, H, S, Dh]
order — each (layer, batch, head) panel's [S, Dh] block contiguous in
HBM — instead of the einsum-oriented ``{4,2,1,3,0}`` layout it picks when
a ``dynamic_update_slice`` write anchors the carry (measured round 4:
that layout S-strides cache reads by 12 KB and capped batch-8 decode at
2.6x batch-1 vs a ~5x streaming roofline; PROFILE_DECODE.md).

Why manual DMA instead of a gridded ``pallas_call``: the gridded decode
kernels measured ~2 us of per-grid-cell overhead, which at 125M shapes
(40 cells/layer) cost 5x more than the cache streaming itself. Here the
whole layer-step is ONE invocation: a dynamic ``fori_loop`` walks the
VALID prefix of the cache in token chunks (one strided DMA covers all
batch rows), double-buffered so the VPU/MXU math overlaps the next
chunk's fetch, with the online-softmax state in VMEM scratch.

Head-dim handling: Mosaic requires DMA slices of the minor dim to be
128-aligned, so for Dh < 128 the cache is VIEWED as token-pairs
``[L, B, Hkv, S/pair, Dh*pair]`` (a free bitcast of the row-major
buffer; ``pair = 128 // Dh``). Packed sub-tokens are never interleaved
back: each of the ``pair`` lane slices keeps its own position mask and
feeds the shared online-softmax state. The new token's write is a
read-modify-write of the 8-aligned pair-row window (HBM tiling forbids
single-row writes), a ~100 KB round-trip per layer step.

MHA (rep == 1) scores/PV run as VPU broadcast-multiply + reduce;
GQA (rep > 1) runs batched MXU ``dot_general`` ([rep, Dh] x [Dh, CS]
slabs per kv head). Serving-only: no VJP (training uses
ops/flash_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = float("-inf")

# per-slot chunk budget: 4 chunk buffers live (2 slots x {K, V}) plus the
# compute temporaries of one chunk. The kernel raises Mosaic's scoped
# VMEM limit (vmem_limit_bytes below) past the 16 MB default, so the
# budget targets covering all of B in ONE batch group (one DMA warmup
# stall per layer instead of B/bg).
_CHUNK_BUDGET = 3_300_000
_VMEM_LIMIT = 40 * 1024 * 1024


def _compiler_params(vmem_bytes: int = _VMEM_LIMIT):
    try:
        return pltpu.CompilerParams(vmem_limit_bytes=vmem_bytes)
    except Exception:  # older naming (flash_attention._grid_params idiom)
        return pltpu.TPUCompilerParams(vmem_limit_bytes=vmem_bytes)


def supports(hq: int, hkv: int, s_max: int, dh: int) -> bool:
    """Shapes the fused kernel can stream: minor dim must tile to 128
    (dh a multiple of 128, or dh*pair == 128 with s_max % pair == 0)."""
    if hq % hkv:
        return False
    if dh >= 128:
        return dh % 128 == 0 and s_max % 128 == 0
    # s_max % 128 == 0 implies s_max % (128 // dh) == 0 for any dh | 128
    return 128 % dh == 0 and s_max % 128 == 0


def _plan(b: int, hkv: int, s_max: int, dh: int, itemsize: int):
    """(bg, cs): batch-group and S-chunk (token) sizes. One DMA moves a
    [bg, hkv, (cs/pair), dh*pair] chunk — exactly bg*hkv*cs*dh elements
    (the packed view keeps the minor dim >= 128 lanes, so no VMEM lane
    padding). Prefer covering all of B per DMA (fewer loop iterations,
    one warmup stall) and the fattest cs that divides s_max."""

    def bytes_of(bg, cs):
        return bg * hkv * cs * dh * itemsize

    for bg in (b, b // 2, b // 4, b // 8, 1):
        if bg < 1 or b % max(bg, 1):
            continue
        for cs in (512, 256, 128):
            if s_max % cs == 0 and bytes_of(bg, cs) <= _CHUNK_BUDGET:
                return bg, cs
    return 1, 128


def _resolve_plan(b: int, hkv: int, s_max: int, dh: int, itemsize: int,
                  override=None):
    """(bg, cs, vmem_bytes, mha) for one fused_decode_step geometry:
    the measured artifact entry (ops/autotune.py) when one exists for
    this backend+shape and VALIDATES against the live shape, else the
    hand-picked :func:`_plan` constants. ``mha`` picks the rep==1
    score/PV engine — "mxu" (default: [1, Dh] x [Dh, CS] slabs like the
    GQA path, ISSUE 12's fused-decode shave) or "vpu" (the pre-ISSUE-12
    broadcast-multiply+reduce, kept plan-selectable so the autotuner
    can measure both). ``override`` is the micro-bench harness's
    candidate entry — same schema, same validation."""
    from deepspeed_tpu.ops import autotune

    ent = override
    if ent is None:
        ent = autotune.lookup(
            "decode_step", autotune.decode_key(b, hkv, s_max, dh, itemsize))
    bg, cs = _plan(b, hkv, s_max, dh, itemsize)
    vmem, mha = _VMEM_LIMIT, "mxu"
    if ent:
        try:
            bg2 = int(ent.get("bg", bg))
            cs2 = int(ent.get("cs", cs))
            # re-validate against the live shape: a stale artifact may
            # cost performance, never a mis-shaped DMA
            if (bg2 >= 1 and b % bg2 == 0 and cs2 >= 128
                    and cs2 % 128 == 0 and s_max % cs2 == 0):
                bg, cs = bg2, cs2
            vmem, mha = _entry_vmem_mha(ent, vmem, mha)
        except Exception:
            pass
    return bg, cs, vmem, mha


def _entry_vmem_mha(ent: dict, vmem: int, mha: str):
    """Shared artifact-entry parsing for the per-kernel tunables both
    decode resolvers honor: the clamped VMEM scope and the rep==1
    score/PV engine (one implementation, so the two kernels can never
    diverge in how they read the same schema).  The clamp bounds come
    from the per-generation table in ops/autotune.py — the same table
    the `vmem-budget` lint pass checks committed plans against."""
    from deepspeed_tpu.ops import autotune

    vmem = max(autotune.DEFAULT_VMEM_MB,
               min(int(ent.get("vmem_mb", vmem >> 20)),
                   autotune.SCOPED_VMEM_MAX_MB)) << 20
    if ent.get("mha") in ("mxu", "vpu"):
        mha = ent["mha"]
    return vmem, mha


def _resolve_block_plan(b: int, hkv: int, bs: int, dh: int, itemsize: int,
                        override=None):
    """(vmem_bytes, mha) for one fused_block_decode_step geometry (the
    block kernel's chunk size IS the pool's block size, so only the
    VMEM scope and the rep==1 engine are tunable)."""
    from deepspeed_tpu.ops import autotune

    ent = override
    if ent is None:
        ent = autotune.lookup(
            "block_decode_step",
            autotune.block_decode_key(b, hkv, bs, dh, itemsize))
    vmem, mha = _VMEM_LIMIT, "mxu"
    if ent:
        try:
            vmem, mha = _entry_vmem_mha(ent, vmem, mha)
        except Exception:
            pass
    return vmem, mha


def _kernel(layer_ref, idx_ref, q_ref, kn_ref, vn_ref, _kin_ref, _vin_ref,
            attn_ref, k_ref, v_ref,
            kbuf, vbuf, kwin, vwin, m_ref, l_ref, acc_ref, wsem, rsem,
            *, b: int, bg: int, cs: int, hq: int, hkv: int, dh: int,
            pair: int, scale: float, per_slot: bool, mha: str = "mxu"):
    layer = layer_ref[0]
    idx = idx_ref[0]
    rep = hq // hkv
    csp = cs // pair          # pair-rows per chunk
    dhp = dh * pair           # packed minor dim (>= 128)

    # ---- write the new token's K/V into the cache (in place: k_ref/v_ref
    # alias the input cache buffers). HBM tiling forbids single-row
    # writes, so read-modify-write the 8-aligned pair-row window (fetch ->
    # vector-select insert -> write back). The write is for FUTURE steps
    # only and runs fully async: this step's attention walk splices the
    # new token into the loaded chunk IN-REGISTER (see `body`), so no
    # read waits on the write-back (a serialized RMW measured +0.13
    # ms/tok at B=1 — pure DMA latency, 12 layers x 4 chained waits).
    #
    # per_slot (continuous batching): idx_ref is a [B] vector of per-slot
    # valid lengths — each row's window is its own DMA (rows' write
    # positions are unrelated), and the splice/position masks below go
    # per-row. The chunk walk streams each batch group to the GROUP MAX
    # length (shorter slots' tails are masked, not skipped: one strided
    # DMA still covers all rows of the group).
    if per_slot:
        w0s = [(idx_ref[i] // pair // 8) * 8 for i in range(b)]

        def kdma(i):
            return pltpu.make_async_copy(
                k_ref.at[layer, pl.ds(i, 1), :, pl.ds(w0s[i], 8), :],
                kwin.at[pl.ds(i, 1)], wsem.at[0, i])

        def vdma(i):
            return pltpu.make_async_copy(
                v_ref.at[layer, pl.ds(i, 1), :, pl.ds(w0s[i], 8), :],
                vwin.at[pl.ds(i, 1)], wsem.at[1, i])

        for i in range(b):
            kdma(i).start()
            vdma(i).start()

        def finish_write():
            for i in range(b):
                kdma(i).wait()
                vdma(i).wait()
            bi = jax.lax.broadcasted_iota(jnp.int32, (b, hkv, 8, dhp), 0)
            ri = jax.lax.broadcasted_iota(jnp.int32, (b, hkv, 8, dhp), 2)
            li = jax.lax.broadcasted_iota(jnp.int32, (b, hkv, 8, dhp), 3)
            sel = bi < 0  # all-false
            for i in range(b):
                idx_i = idx_ref[i]
                sel_i = (bi == i) & (ri == jax.lax.rem(idx_i // pair, 8))
                if pair > 1:
                    sel_i &= (li // dh == idx_i - (idx_i // pair) * pair)
                sel |= sel_i
            kwin[...] = jnp.where(sel, kn_ref[...], kwin[...])
            vwin[...] = jnp.where(sel, vn_ref[...], vwin[...])
            for i in range(b):
                pltpu.make_async_copy(
                    kwin.at[pl.ds(i, 1)],
                    k_ref.at[layer, pl.ds(i, 1), :, pl.ds(w0s[i], 8), :],
                    wsem.at[0, i]).start()
                pltpu.make_async_copy(
                    vwin.at[pl.ds(i, 1)],
                    v_ref.at[layer, pl.ds(i, 1), :, pl.ds(w0s[i], 8), :],
                    wsem.at[1, i]).start()
    else:
        w0 = (idx // pair // 8) * 8
        fk = pltpu.make_async_copy(
            k_ref.at[layer, :, :, pl.ds(w0, 8), :], kwin, wsem.at[0, 0])
        fv = pltpu.make_async_copy(
            v_ref.at[layer, :, :, pl.ds(w0, 8), :], vwin, wsem.at[1, 0])
        fk.start()
        fv.start()

        def finish_write():
            """Insert the token into the fetched window and write it back —
            called after the first chunk DMAs are in flight."""
            fk.wait()
            fv.wait()
            row = idx // pair - w0
            half = idx - (idx // pair) * pair
            sel = (jax.lax.broadcasted_iota(
                jnp.int32, (b, hkv, 8, dhp), 2) == row)
            if pair > 1:
                sel &= (jax.lax.broadcasted_iota(
                    jnp.int32, (b, hkv, 8, dhp), 3) // dh == half)
            kwin[...] = jnp.where(sel, kn_ref[...], kwin[...])
            vwin[...] = jnp.where(sel, vn_ref[...], vwin[...])
            pltpu.make_async_copy(
                kwin, k_ref.at[layer, :, :, pl.ds(w0, 8), :],
                wsem.at[0, 0]).start()
            pltpu.make_async_copy(
                vwin, v_ref.at[layer, :, :, pl.ds(w0, 8), :],
                wsem.at[1, 0]).start()

    nchunks = idx // cs + 1  # valid-prefix walk: dead chunks never fetched

    for g in range(b // bg):  # static unroll over batch groups
        b0 = g * bg
        if per_slot:
            gmax = idx_ref[b0]
            for i in range(1, bg):
                gmax = jnp.maximum(gmax, idx_ref[b0 + i])
            nchunks = gmax // cs + 1

        def group_idx_vec(shape):
            """int32 [shape] with entry (i, ...) == idx_ref[b0 + i] —
            per-row lengths broadcast into a vector register (built by
            bg unrolled selects: SMEM scalars can't gather)."""
            bi = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
            out = jnp.zeros(shape, jnp.int32)
            for i in range(bg):
                out = jnp.where(bi == i, idx_ref[b0 + i], out)
            return out

        def chunk_dma(slot, c, src, buf, t):
            return pltpu.make_async_copy(
                src.at[layer, pl.ds(b0, bg), :, pl.ds(c * csp, csp), :],
                buf.at[slot], rsem.at[slot, t])

        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

        chunk_dma(0, 0, k_ref, kbuf, 0).start()
        chunk_dma(0, 0, v_ref, vbuf, 1).start()
        if g == 0:
            finish_write()  # overlaps with chunk 0's flight
        qv = q_ref[pl.ds(b0, bg)]                    # [bg, Hq, 1, Dh] bf16
        # (the unit dim comes pre-shaped from the wrapper: Mosaic cannot
        # reshape bf16 vectors to add one before the minor dim)

        def body(c, _, splice=False):
            slot = jax.lax.rem(c, 2)
            nxt = 1 - slot

            @pl.when(c + 1 < nchunks)
            def _prefetch():
                chunk_dma(nxt, c + 1, k_ref, kbuf, 0).start()
                chunk_dma(nxt, c + 1, v_ref, vbuf, 1).start()

            # splice mask (shared by K now and V below): each row's new
            # token lands at its own position (per_slot: any chunk of the
            # group walk; uniform: only the final chunk — the prefix walk
            # never pays the vector work)
            spl = None
            if per_slot:
                idxm = group_idx_vec((bg, hkv, csp, dhp))
                rowg = c * csp + jax.lax.broadcasted_iota(
                    jnp.int32, (bg, hkv, csp, dhp), 2)
                spl = rowg == idxm // pair
                if pair > 1:
                    spl &= (jax.lax.broadcasted_iota(
                        jnp.int32, (bg, hkv, csp, dhp), 3) // dh
                            == idxm - (idxm // pair) * pair)
            elif splice:
                # in-register splice of the new token (its async cache
                # write may still be in flight; every other row is
                # unchanged, so a read/write race can only return
                # identical bytes)
                rowg = c * csp + jax.lax.broadcasted_iota(
                    jnp.int32, (bg, hkv, csp, dhp), 2)
                spl = rowg == idx // pair
                if pair > 1:
                    spl &= (jax.lax.broadcasted_iota(
                        jnp.int32, (bg, hkv, csp, dhp), 3) // dh
                            == idx - (idx // pair) * pair)

            # K first: the scores + running-max update run while the V
            # half of the chunk is still in flight (ISSUE 12 shave — the
            # old joint wait serialized ~half the chunk DMA behind the
            # VPU/MXU math it could hide under)
            chunk_dma(slot, c, k_ref, kbuf, 0).wait()
            kc = kbuf[slot]                         # [bg, Hkv, CSP, Dh*pair]
            # bf16: products run in bf16 with f32 accumulation — the same
            # precision contract as the einsum path's MXU (bf16 multiply,
            # f32 accumulate); a full f32 materialization of both chunks
            # measured ~2x the VPU time
            if spl is not None:
                kc = jnp.where(spl, kn_ref[pl.ds(b0, bg)], kc)
            # scores for each packed lane slice (its own position stream)
            ss = []
            for h in range(pair):
                k = kc[..., h * dh:(h + 1) * dh]    # [bg, Hkv, CSP, Dh]
                if rep == 1 and mha == "vpu":
                    s = jnp.sum(qv * k, -1,
                                dtype=jnp.float32)         # VPU [bg, H, CSP]
                else:
                    # MXU [rep, Dh] x [Dh, CS] slabs per kv head (rep==1
                    # degenerates to [1, Dh] matvecs — the ISSUE 12
                    # default; the autotuned plan can select "vpu" back)
                    qg = qv.reshape(bg * hkv, rep, dh)     # 1 batch dim
                    kg = k.reshape(bg * hkv, csp, dh)      # (Mosaic limit)
                    s = jax.lax.dot_general(               # MXU
                        qg, kg, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)
                    s = s.reshape(bg, hq, csp)
                s = s * scale
                pos = c * cs + pair * jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 2) + h
                bound = group_idx_vec(s.shape) if per_slot else idx
                ss.append(jnp.where(pos <= bound, s, _NEG))

            m_prev = m_ref[...]                            # [bg, Hq]
            m_new = m_prev
            for s in ss:
                m_new = jnp.maximum(m_new, s.max(-1))
            corr = jnp.exp(m_prev - m_new)
            l_new = l_ref[...] * corr
            acc = acc_ref[...] * corr[:, :, None]
            ps = [jnp.exp(s - m_new[:, :, None]) for s in ss]
            for p in ps:
                l_new = l_new + p.sum(-1)

            chunk_dma(slot, c, v_ref, vbuf, 1).wait()
            vc = vbuf[slot]
            if spl is not None:
                vc = jnp.where(spl, vn_ref[pl.ds(b0, bg)], vc)
            for h, p in enumerate(ps):
                v = vc[..., h * dh:(h + 1) * dh]
                if rep == 1 and mha == "vpu":
                    pb = p[:, :, :, None].astype(v.dtype)  # None-insert in
                    # f32 (bf16 unit-dim reshape is unsupported), cast after
                    pv = jnp.sum(pb * v, 2,
                                 dtype=jnp.float32)        # VPU [bg, H, Dh]
                else:
                    pg = p.reshape(bg * hkv, rep, csp).astype(v.dtype)
                    vg = v.reshape(bg * hkv, csp, dh)
                    pv = jax.lax.dot_general(              # MXU
                        pg, vg, (((2,), (1,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)
                    pv = pv.reshape(bg, hq, dh)
                acc = acc + pv
            l_ref[...] = l_new
            acc_ref[...] = acc
            m_ref[...] = m_new
            return 0

        if per_slot:
            # every chunk splices (the per-row masks gate it), so the walk
            # is one uniform loop to the group-max chunk count
            jax.lax.fori_loop(0, nchunks, body, 0)
        else:
            jax.lax.fori_loop(0, nchunks - 1, body, 0)
            body(nchunks - 1, 0, splice=True)
        l_safe = jnp.maximum(l_ref[...], 1e-20)
        attn_ref[pl.ds(b0, bg)] = (acc_ref[...] / l_safe[:, :, None]) \
            .astype(attn_ref.dtype)

    # drain the async write-back before the kernel exits
    if per_slot:
        for i in range(b):
            pltpu.make_async_copy(
                kwin.at[pl.ds(i, 1)],
                k_ref.at[layer, pl.ds(i, 1), :, pl.ds(w0s[i], 8), :],
                wsem.at[0, i]).wait()
            pltpu.make_async_copy(
                vwin.at[pl.ds(i, 1)],
                v_ref.at[layer, pl.ds(i, 1), :, pl.ds(w0s[i], 8), :],
                wsem.at[1, i]).wait()
    else:
        pltpu.make_async_copy(
            kwin, k_ref.at[layer, :, :, pl.ds(w0, 8), :], wsem.at[0, 0]).wait()
        pltpu.make_async_copy(
            vwin, v_ref.at[layer, :, :, pl.ds(w0, 8), :], wsem.at[1, 0]).wait()


def supports_block(hq: int, hkv: int, block_size: int, dh: int) -> bool:
    """Shapes the fused BLOCK-TABLE kernel can stream: minor dim must
    tile to 128 lanes (dh % 128 == 0, or dh*pair == 128), and each
    block's pair-row count must cover whole 8-row HBM tiles (the new
    token's write is an 8-aligned window RMW inside one block)."""
    if hq % hkv:
        return False
    if dh >= 128:
        return dh % 128 == 0 and block_size % 8 == 0
    return 128 % dh == 0 and block_size % (8 * (128 // dh)) == 0


def _quantize_token(x, kv_dtype: str, cdtype):
    """In-register quantization of one packed new-token row
    ``x [B, Hkv, 1, Dh*pair]``: a direct call into the einsum path's
    quantizer (serving/kv_quant.kv_quantize_keepdims — ONE shared
    implementation, so stored-byte bit-identity between the fused and
    einsum paths holds by construction). The pair lane slices are
    COPIES of the same Dh values, so the amax over the packed row
    equals the unpacked row's and one per-(row, head) scale covers
    every copy. Returns ``(payload [B, Hkv, 1, Dh*pair],
    scale [B, Hkv, 1, 1] bf16, deq [B, Hkv, 1, Dh*pair] cdtype)``
    where ``deq`` is the quantize->dequantize image — the value every
    LATER step will read, spliced into THIS step's chunks so kernel
    and einsum attend identically."""
    from deepspeed_tpu.serving.kv_quant import kv_quantize_keepdims

    payload, s = kv_quantize_keepdims(x, kv_dtype)
    deq = (payload.astype(jnp.float32)
           * s.astype(jnp.float32)).astype(cdtype)
    return payload, s, deq


def _block_kernel(*refs, b: int, mb: int, csp: int, hq: int, hkv: int,
                  dh: int, pair: int, scale: float, quant: bool,
                  kv_dtype: str, mha: str):
    """Block-paged decode layer-step (the block-table analog of
    :func:`_kernel`'s per_slot path): each batch row's KV lives in the
    pool blocks named by its ``tbl_ref[i]`` row, so both the new token's
    window RMW and the streaming walk indirect through the table —
    which is SMEM DATA, so remapping blocks between steps never
    recompiles. Rows are processed one at a time (serving batches are
    narrow; each row's block chain is its own DMA stream), with the
    same double-buffered fetch + in-register splice + online-softmax
    structure as the slot kernel. Sentinel table entries name the
    pool's garbage row (kv_blocks.BlockKVPool), so inactive slots'
    writes and reads are unconditionally safe — no predication.

    ``quant`` (ISSUE 12): the pools are int8/fp8 payload + pair-grouped
    bf16 scale arrays (serving/kv_quant.py). The chunk walk DMAs 1-byte
    payload blocks (half the streamed bytes of bf16) plus their tiny
    scale rows and dequantizes IN-REGISTER per lane slice; the write
    side quantizes the new token in-register and RMWs the WHOLE tail
    block + its scale row (whole-block windows sidestep int8's 32-row
    HBM tile quantum; a block is at most a few KB). Scores/PV run in
    the compute dtype either way — the quantization lives entirely in
    the DMA boundary."""
    if quant:
        (layer_ref, idx_ref, tbl_ref, q_ref, kn_ref, vn_ref,
         _kqi, _vqi, _ksi, _vsi,
         attn_ref, k_ref, v_ref, ks_ref, vs_ref,
         kbuf, vbuf, ksbuf, vsbuf, kwin, vwin, kswin, vswin,
         m_ref, l_ref, acc_ref, wsem, rsem) = refs
    else:
        (layer_ref, idx_ref, tbl_ref, q_ref, kn_ref, vn_ref,
         _kqi, _vqi,
         attn_ref, k_ref, v_ref,
         kbuf, vbuf, kwin, vwin,
         m_ref, l_ref, acc_ref, wsem, rsem) = refs
    layer = layer_ref[0]
    rep = hq // hkv
    bs = csp * pair           # tokens per block
    dhp = dh * pair
    cdtype = q_ref.dtype

    if quant:
        # quantize the new tokens once, up front (pure vector math —
        # nothing waits on it): payloads/scales for the write-back,
        # dequantized images for the in-register splices
        kq_new, ks_new, kn_spl = _quantize_token(
            kn_ref[...], kv_dtype, cdtype)
        vq_new, vs_new, vn_spl = _quantize_token(
            vn_ref[...], kv_dtype, cdtype)
    else:
        kn_spl, vn_spl = kn_ref[...], vn_ref[...]

    # ---- write each row's new token into its current tail block.
    # bf16: RMW the 8-aligned pair-row window (HBM tiling forbids
    # single-row writes). quant: RMW the WHOLE block + its scale row.
    pbs, w0s = [], []
    for i in range(b):
        pos = idx_ref[i]
        jb = jnp.minimum(pos // bs, mb - 1)
        pbs.append(tbl_ref[i, jb])
        w0s.append(0 if quant else (pos % bs // pair // 8) * 8)
    nwin = csp if quant else 8

    def kdma(i):
        return pltpu.make_async_copy(
            k_ref.at[layer, pl.ds(pbs[i], 1), :, pl.ds(w0s[i], nwin), :],
            kwin.at[pl.ds(i, 1)], wsem.at[0, i])

    def vdma(i):
        return pltpu.make_async_copy(
            v_ref.at[layer, pl.ds(pbs[i], 1), :, pl.ds(w0s[i], nwin), :],
            vwin.at[pl.ds(i, 1)], wsem.at[1, i])

    def ksdma(i):
        return pltpu.make_async_copy(
            ks_ref.at[layer, pl.ds(pbs[i], 1), :, :, :],
            kswin.at[pl.ds(i, 1)], wsem.at[2, i])

    def vsdma(i):
        return pltpu.make_async_copy(
            vs_ref.at[layer, pl.ds(pbs[i], 1), :, :, :],
            vswin.at[pl.ds(i, 1)], wsem.at[3, i])

    wdmas = [kdma, vdma] + ([ksdma, vsdma] if quant else [])
    for i in range(b):
        for mk in wdmas:
            mk(i).start()

    def finish_write():
        for i in range(b):
            for mk in wdmas:
                mk(i).wait()
        bi = jax.lax.broadcasted_iota(jnp.int32, (b, hkv, nwin, dhp), 0)
        ri = jax.lax.broadcasted_iota(jnp.int32, (b, hkv, nwin, dhp), 2)
        li = jax.lax.broadcasted_iota(jnp.int32, (b, hkv, nwin, dhp), 3)
        sel = bi < 0  # all-false
        for i in range(b):
            r = jax.lax.rem(idx_ref[i], bs)
            row = r // pair if quant else jax.lax.rem(r // pair, 8)
            sel_i = (bi == i) & (ri == row)
            if pair > 1:
                sel_i &= (li // dh == jax.lax.rem(r, pair))
            sel |= sel_i
        if quant:
            kwin[...] = jnp.where(sel, kq_new, kwin[...])
            vwin[...] = jnp.where(sel, vq_new, vwin[...])
            # scale row splice: pair-grouped [b, Hkv, pair, csp] —
            # token r sits at [.., r % pair, r // pair]
            sbi = jax.lax.broadcasted_iota(
                jnp.int32, (b, hkv, pair, csp), 0)
            spi = jax.lax.broadcasted_iota(
                jnp.int32, (b, hkv, pair, csp), 2)
            sri = jax.lax.broadcasted_iota(
                jnp.int32, (b, hkv, pair, csp), 3)
            sel_s = sbi < 0
            for i in range(b):
                r = jax.lax.rem(idx_ref[i], bs)
                sel_s |= ((sbi == i) & (spi == jax.lax.rem(r, pair))
                          & (sri == r // pair))
            kswin[...] = jnp.where(sel_s, ks_new, kswin[...])
            vswin[...] = jnp.where(sel_s, vs_new, vswin[...])
        else:
            kwin[...] = jnp.where(sel, kn_ref[...], kwin[...])
            vwin[...] = jnp.where(sel, vn_ref[...], vwin[...])
        for i in range(b):
            pltpu.make_async_copy(
                kwin.at[pl.ds(i, 1)],
                k_ref.at[layer, pl.ds(pbs[i], 1), :,
                         pl.ds(w0s[i], nwin), :],
                wsem.at[0, i]).start()
            pltpu.make_async_copy(
                vwin.at[pl.ds(i, 1)],
                v_ref.at[layer, pl.ds(pbs[i], 1), :,
                         pl.ds(w0s[i], nwin), :],
                wsem.at[1, i]).start()
            if quant:
                pltpu.make_async_copy(
                    kswin.at[pl.ds(i, 1)],
                    ks_ref.at[layer, pl.ds(pbs[i], 1), :, :, :],
                    wsem.at[2, i]).start()
                pltpu.make_async_copy(
                    vswin.at[pl.ds(i, 1)],
                    vs_ref.at[layer, pl.ds(pbs[i], 1), :, :, :],
                    wsem.at[3, i]).start()

    # ---- per-row valid-block walk (chunk == one pool block)
    for i in range(b):
        idx_i = idx_ref[i]
        nblk = idx_i // bs + 1

        def chunk_dma(slot, j, src, buf, t):
            pb = tbl_ref[i, jnp.minimum(j, mb - 1)]
            return pltpu.make_async_copy(
                src.at[layer, pl.ds(pb, 1), :, :, :],
                buf.at[slot], rsem.at[slot, t])

        def start_chunk(slot, j):
            chunk_dma(slot, j, k_ref, kbuf, 0).start()
            chunk_dma(slot, j, v_ref, vbuf, 1).start()
            if quant:
                chunk_dma(slot, j, ks_ref, ksbuf, 2).start()
                chunk_dma(slot, j, vs_ref, vsbuf, 3).start()

        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)
        start_chunk(0, 0)
        if i == 0:
            finish_write()  # overlaps with row 0 / chunk 0's flight
        qv = q_ref[pl.ds(i, 1)]                      # [1, Hq, 1, Dh]

        def half_slice(buf_val, sbuf_val, spl_val, c, h):
            """Lane slice ``h`` of a loaded chunk in the compute dtype:
            dequantized against its pair-grouped scale row (quant) or
            sliced directly (bf16), with the new token spliced in at
            its own (row, half)."""
            x = buf_val[..., h * dh:(h + 1) * dh]    # [1, Hkv, CSP, Dh]
            if quant:
                sc = sbuf_val[:, :, h, :]            # [1, Hkv, CSP]
                x = (x.astype(cdtype) * sc[..., None].astype(cdtype))
            rowg = c * csp + jax.lax.broadcasted_iota(
                jnp.int32, (1, hkv, csp, dh), 2)
            spl = rowg == idx_i // pair
            if pair > 1:
                spl &= jnp.full((1, hkv, csp, dh),
                                jax.lax.rem(idx_i, pair) == h)
            # spl_val is a traced VALUE (not a ref); i is a static
            # python index, so plain slicing selects the row
            return jnp.where(
                spl, spl_val[i:i + 1][..., h * dh:(h + 1) * dh], x)

        def body(c, _):
            slot = jax.lax.rem(c, 2)
            nxt = 1 - slot

            @pl.when(c + 1 < nblk)
            def _prefetch():
                start_chunk(nxt, c + 1)

            # K first: scores + running-max math run under the V half's
            # remaining flight time (ISSUE 12 fused-decode shave)
            chunk_dma(slot, c, k_ref, kbuf, 0).wait()
            if quant:
                chunk_dma(slot, c, ks_ref, ksbuf, 2).wait()
            kq = kbuf[slot]                          # [1, Hkv, CSP, Dh*pair]
            ksc = ksbuf[slot] if quant else None
            ss = []
            for h in range(pair):
                k = half_slice(kq, ksc, kn_spl, c, h)
                if rep == 1 and mha == "vpu":
                    s = jnp.sum(qv * k, -1, dtype=jnp.float32)
                else:
                    qg = qv.reshape(hkv, rep, dh)
                    kg = k.reshape(hkv, csp, dh)
                    s = jax.lax.dot_general(
                        qg, kg, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)
                    s = s.reshape(1, hq, csp)
                s = s * scale
                pos = c * bs + pair * jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 2) + h
                ss.append(jnp.where(pos <= idx_i, s, _NEG))
            m_prev = m_ref[...]                      # [1, Hq]
            m_new = m_prev
            for s in ss:
                m_new = jnp.maximum(m_new, s.max(-1))
            corr = jnp.exp(m_prev - m_new)
            l_new = l_ref[...] * corr
            acc = acc_ref[...] * corr[:, :, None]
            ps = [jnp.exp(s - m_new[:, :, None]) for s in ss]
            for p in ps:
                l_new = l_new + p.sum(-1)

            chunk_dma(slot, c, v_ref, vbuf, 1).wait()
            if quant:
                chunk_dma(slot, c, vs_ref, vsbuf, 3).wait()
            vq = vbuf[slot]
            vsc = vsbuf[slot] if quant else None
            for h, p in enumerate(ps):
                v = half_slice(vq, vsc, vn_spl, c, h)
                if rep == 1 and mha == "vpu":
                    pb_ = p[:, :, :, None].astype(v.dtype)
                    pv = jnp.sum(pb_ * v, 2, dtype=jnp.float32)
                else:
                    pg = p.reshape(hkv, rep, csp).astype(v.dtype)
                    vg = v.reshape(hkv, csp, dh)
                    pv = jax.lax.dot_general(
                        pg, vg, (((2,), (1,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)
                    pv = pv.reshape(1, hq, dh)
                acc = acc + pv
            l_ref[...] = l_new
            acc_ref[...] = acc
            m_ref[...] = m_new
            return 0

        jax.lax.fori_loop(0, nblk, body, 0)
        l_safe = jnp.maximum(l_ref[...], 1e-20)
        attn_ref[pl.ds(i, 1)] = (acc_ref[...] / l_safe[:, :, None]) \
            .astype(attn_ref.dtype)

    # drain the async write-back before the kernel exits
    for i in range(b):
        pltpu.make_async_copy(
            kwin.at[pl.ds(i, 1)],
            k_ref.at[layer, pl.ds(pbs[i], 1), :, pl.ds(w0s[i], nwin), :],
            wsem.at[0, i]).wait()
        pltpu.make_async_copy(
            vwin.at[pl.ds(i, 1)],
            v_ref.at[layer, pl.ds(pbs[i], 1), :, pl.ds(w0s[i], nwin), :],
            wsem.at[1, i]).wait()
        if quant:
            pltpu.make_async_copy(
                kswin.at[pl.ds(i, 1)],
                ks_ref.at[layer, pl.ds(pbs[i], 1), :, :, :],
                wsem.at[2, i]).wait()
            pltpu.make_async_copy(
                vswin.at[pl.ds(i, 1)],
                vs_ref.at[layer, pl.ds(pbs[i], 1), :, :, :],
                wsem.at[3, i]).wait()


def fused_block_decode_step(q: jax.Array, k_pool, v_pool,
                            k_new: jax.Array, v_new: jax.Array,
                            layer, idx, block_table, *,
                            scale: Optional[float] = None,
                            interpret: Optional[bool] = None,
                            plan: Optional[dict] = None):
    """One decode layer-step against the BLOCK-PAGED pool (ISSUE 6).

    q:             [B, 1, Hq, Dh]   — the new token's queries
    k_pool/v_pool: [L, N+1, Hkv, bs(/pair), Dh(*pair)] block pools
                   (serving/kv_blocks.BlockKVPool; last row = garbage),
                   or the quantized ``{"q": payload, "s": scales}``
                   pytrees (ISSUE 12, serving/kv_quant.py) — the kernel
                   then streams 1-byte payload chunks and dequantizes
                   in-register, and quantizes the new token on store.
    k_new/v_new:   [B, 1, Hkv, Dh]  — the new token's K/V (unwritten)
    layer:         scalar int32
    idx:           [B] int32 per-slot valid lengths
    block_table:   [B, MB] int32 — TRACED data, one compiled program
                   serves every block assignment.
    plan:          optional measured-plan override (the autotune
                   harness's candidate; ops/autotune.py entries are
                   consulted otherwise).

    Returns ``(attn [B, 1, Hq, Dh], k_pool, v_pool)`` with the pools
    updated in place (the returned pools alias the inputs).
    """
    b, t, hq, dh = q.shape
    assert t == 1, "fused_block_decode_step is the single-token path"
    quant = isinstance(k_pool, dict)
    kq_pool = k_pool["q"] if quant else k_pool
    vq_pool = v_pool["q"] if quant else v_pool
    l, n_phys, hkv, bsp, d_last = kq_pool.shape
    pair = d_last // dh
    bs = bsp * pair
    assert supports_block(hq, hkv, bs, dh), (hq, hkv, bs, dh)
    want_pair = 128 // dh if dh < 128 else 1
    assert pair == want_pair, (d_last, dh)  # router checks kv_pack_factor
    sc = float(scale) if scale is not None else dh ** -0.5
    store_dtype = kq_pool.dtype
    kv_dtype = ("int8" if store_dtype == jnp.int8 else "fp8") if quant \
        else "compute"
    vmem, mha = _resolve_block_plan(
        b, hkv, bs, dh, jnp.dtype(store_dtype).itemsize, override=plan)

    qf = q.transpose(0, 2, 1, 3)                   # [B, Hq, 1, Dh]
    kn = k_new.transpose(0, 2, 1, 3)               # [B, Hkv, 1, Dh]
    vn = v_new.transpose(0, 2, 1, 3)
    if pair > 1:
        kn = jnp.concatenate([kn] * pair, axis=-1)
        vn = jnp.concatenate([vn] * pair, axis=-1)
    layer_a = jnp.asarray(layer, jnp.int32).reshape(1)
    idx_a = jnp.asarray(idx, jnp.int32).reshape(-1)
    assert idx_a.shape[0] == b, (idx_a.shape, b)
    tbl = jnp.asarray(block_table, jnp.int32)
    mb = tbl.shape[1]

    kernel = functools.partial(
        _block_kernel, b=b, mb=mb, csp=bsp, hq=hq, hkv=hkv, dh=dh,
        pair=pair, scale=sc, quant=quant, kv_dtype=kv_dtype, mha=mha)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),   # layer
        pl.BlockSpec(memory_space=pltpu.SMEM),   # idx
        pl.BlockSpec(memory_space=pltpu.SMEM),   # block table
        pl.BlockSpec(memory_space=pltpu.VMEM),   # q
        pl.BlockSpec(memory_space=pltpu.VMEM),   # k_new
        pl.BlockSpec(memory_space=pltpu.VMEM),   # v_new
        pl.BlockSpec(memory_space=pl.ANY),       # k payload (aliased)
        pl.BlockSpec(memory_space=pl.ANY),       # v payload (aliased)
    ]
    out_specs = [pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pl.ANY),
                 pl.BlockSpec(memory_space=pl.ANY)]
    out_shape = [jax.ShapeDtypeStruct((b, hq, dh), q.dtype),
                 jax.ShapeDtypeStruct(kq_pool.shape, kq_pool.dtype),
                 jax.ShapeDtypeStruct(vq_pool.shape, vq_pool.dtype)]
    nwin = bsp if quant else 8
    scratch = [
        pltpu.VMEM((2, 1, hkv, bsp, dh * pair), kq_pool.dtype),
        pltpu.VMEM((2, 1, hkv, bsp, dh * pair), vq_pool.dtype),
    ]
    operands = [layer_a, idx_a, tbl, qf, kn, vn, kq_pool, vq_pool]
    if quant:
        ks_pool, vs_pool = k_pool["s"], v_pool["s"]
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),   # k scales
                     pl.BlockSpec(memory_space=pl.ANY)]   # v scales
        out_specs += [pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY)]
        out_shape += [jax.ShapeDtypeStruct(ks_pool.shape, ks_pool.dtype),
                      jax.ShapeDtypeStruct(vs_pool.shape, vs_pool.dtype)]
        operands += [ks_pool, vs_pool]
        scratch += [  # scale chunk double-buffers
            pltpu.VMEM((2, 1, hkv, pair, bsp), ks_pool.dtype),
            pltpu.VMEM((2, 1, hkv, pair, bsp), vs_pool.dtype),
        ]
        aliases = {6: 1, 7: 2, 8: 3, 9: 4}
    else:
        aliases = {6: 1, 7: 2}
    scratch += [
        pltpu.VMEM((b, hkv, nwin, dh * pair), kq_pool.dtype),  # write window
        pltpu.VMEM((b, hkv, nwin, dh * pair), vq_pool.dtype),
    ]
    if quant:
        scratch += [  # scale-row write windows
            pltpu.VMEM((b, hkv, pair, bsp), k_pool["s"].dtype),
            pltpu.VMEM((b, hkv, pair, bsp), v_pool["s"].dtype),
        ]
    scratch += [
        pltpu.VMEM((1, hq), jnp.float32),                  # running max
        pltpu.VMEM((1, hq), jnp.float32),                  # running sum
        pltpu.VMEM((1, hq, dh), jnp.float32),              # accumulator
        pltpu.SemaphoreType.DMA((4 if quant else 2, b)),   # write sems
        pltpu.SemaphoreType.DMA((2, 4 if quant else 2)),   # read sems
    ]
    out = pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        input_output_aliases=aliases,
        compiler_params=_compiler_params(vmem),
        interpret=(jax.default_backend() != "tpu" if interpret is None
                   else interpret),
    )(*operands)
    if quant:
        attn, k_out, v_out, ks_out, vs_out = out
        return (attn[:, None], {"q": k_out, "s": ks_out},
                {"q": v_out, "s": vs_out})
    attn, k_out, v_out = out
    return attn[:, None], k_out, v_out


def fused_decode_step(q: jax.Array, k_full: jax.Array, v_full: jax.Array,
                      k_new: jax.Array, v_new: jax.Array,
                      layer, idx, *, scale: Optional[float] = None,
                      interpret: Optional[bool] = None,
                      plan: Optional[dict] = None):
    """One decode layer-step against the FULL stacked cache.

    q:            [B, 1, Hq, Dh]  — the new token's queries
    k_full/v_full:[L, B, Hkv, S, Dh] head-major stacked caches (carry)
    k_new/v_new:  [B, 1, Hkv, Dh]  — the new token's K/V (not yet written)
    layer:        scalar int32 — layer index
    idx:          scalar int32 first free cache position, or a PER-SLOT
                  [B] int32 vector of valid lengths (continuous batching,
                  serving/engine.py) — each row then writes at and
                  attends over its own prefix, and each batch group
                  streams to the group's max length.
    plan:         optional measured-plan override (the autotune
                  harness's candidate; ops/autotune.py entries are
                  consulted otherwise — ``_resolve_plan``).

    Returns ``(attn [B, 1, Hq, Dh], k_full, v_full)`` with the caches
    updated in place (the returned caches alias the inputs).
    """
    b, t, hq, dh = q.shape
    assert t == 1, "fused_decode_step is the single-token path"
    l, _, hkv, s_rows, d_last = k_full.shape
    pair = d_last // dh          # caller may pass an already-packed cache
    s_max = s_rows * pair
    assert supports(hq, hkv, s_max, dh), (hq, hkv, s_max, dh)
    assert pair in (1, 128 // dh if dh < 128 else 1), (d_last, dh)
    want_pair = 128 // dh if dh < 128 else 1
    sc = float(scale) if scale is not None else dh ** -0.5
    bg, cs, vmem, mha = _resolve_plan(
        b, hkv, s_max, dh, jnp.dtype(k_full.dtype).itemsize, override=plan)

    qf = q.transpose(0, 2, 1, 3)                   # [B, Hq, 1, Dh]
    kn = k_new.transpose(0, 2, 1, 3)               # [B, Hkv, 1, Dh]
    vn = v_new.transpose(0, 2, 1, 3)
    if want_pair > 1:
        # pair-row window select needs the token's Dh values present in
        # every lane slice
        kn = jnp.concatenate([kn] * want_pair, axis=-1)
        vn = jnp.concatenate([vn] * want_pair, axis=-1)
    if pair == want_pair:
        kview, vview = k_full, v_full              # already packed (models
        # allocate the packed form so no repack copy rides the carry)
    else:
        kview = k_full.reshape(l, b, hkv, s_max // want_pair, dh * want_pair)
        vview = v_full.reshape(l, b, hkv, s_max // want_pair, dh * want_pair)
    pair = want_pair
    layer_a = jnp.asarray(layer, jnp.int32).reshape(1)
    idx_a = jnp.asarray(idx, jnp.int32).reshape(-1)
    assert idx_a.shape[0] in (1, b), (idx_a.shape, b)
    per_slot = idx_a.shape[0] > 1  # [1] degenerates to the uniform path

    kernel = functools.partial(
        _kernel, b=b, bg=bg, cs=cs, hq=hq, hkv=hkv, dh=dh, pair=pair,
        scale=sc, per_slot=per_slot, mha=mha)
    attn, k_out, v_out = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # layer
            pl.BlockSpec(memory_space=pltpu.SMEM),   # idx
            pl.BlockSpec(memory_space=pltpu.VMEM),   # q
            pl.BlockSpec(memory_space=pltpu.VMEM),   # k_new
            pl.BlockSpec(memory_space=pltpu.VMEM),   # v_new
            pl.BlockSpec(memory_space=pl.ANY),       # k_full (aliased)
            pl.BlockSpec(memory_space=pl.ANY),       # v_full (aliased)
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, dh), q.dtype),
            jax.ShapeDtypeStruct(kview.shape, k_full.dtype),
            jax.ShapeDtypeStruct(vview.shape, v_full.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, bg, hkv, cs // pair, dh * pair), k_full.dtype),
            pltpu.VMEM((2, bg, hkv, cs // pair, dh * pair), v_full.dtype),
            pltpu.VMEM((b, hkv, 8, dh * pair), k_full.dtype),  # write window
            pltpu.VMEM((b, hkv, 8, dh * pair), v_full.dtype),
            pltpu.VMEM((bg, hq), jnp.float32),                 # running max
            pltpu.VMEM((bg, hq), jnp.float32),                 # running sum
            pltpu.VMEM((bg, hq, dh), jnp.float32),             # accumulator
            # write sems: per-row windows in the per-slot path
            pltpu.SemaphoreType.DMA((2, b if per_slot else 1)),
            pltpu.SemaphoreType.DMA((2, 2)),                   # read sems
        ],
        input_output_aliases={5: 1, 6: 2},
        compiler_params=_compiler_params(vmem),
        interpret=(jax.default_backend() != "tpu" if interpret is None
                   else interpret),
    )(layer_a, idx_a, qf, kn, vn, kview, vview)
    if k_out.shape != k_full.shape:
        k_out = k_out.reshape(k_full.shape)
        v_out = v_out.reshape(v_full.shape)
    return attn[:, None], k_out, v_out
