"""Int8 weight-streaming matmul kernel for memory-bound decode.

Reference counterpart: the dequant-fused int8 GEMV path in
``csrc/transformer/inference`` (pt_binding.cpp vector_matmul + the
dequantization kernels in dequantize.cu) — the reference streams int8
weights through a fused dequant+GEMV so HBM traffic stays 1 byte/weight.

Why a Pallas kernel: XLA will not reliably fuse an ``int8 -> bf16``
convert into a dot operand — measured at GPT-2-125M decode, the
``qdot`` einsum path (convert materialized per layer) made int8 SLOWER
than bf16 (0.53 vs 0.43 ms/tok) because each weight pays int8-read +
bf16-write + bf16-read. Here the int8 tile is DMA'd into VMEM as int8
(1 byte/weight of HBM traffic — the whole point of weight-only
quantization) and upcast in-register on its way into the MXU; the
per-output-column scale multiplies the f32 accumulator once at the end.

Decode shapes: activations are tiny ([B<=16, D]); weights dominate.
The grid walks (E tiles x D tiles) with D innermost so each output tile
accumulates across the contraction in VMEM scratch.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Fat tiles: decode matmuls are weight-streaming-bound and the per-grid-cell
# overhead is what erased the int8 bandwidth win in the first cut (~430
# cells/step at 125M measured ≈ bf16). Blocks are picked as the LARGEST
# divisors of (E, D) under a VMEM byte budget — at 125M every block matmul
# becomes 1 grid cell ([768, 2304] int8 = 1.7 MB); at 6.7B shapes ~2-8
# cells. Budget 8 MB keeps tile + double-buffer + accumulator well under
# the ~16 MB/core VMEM.
MAX_TILE_BYTES = 8 * 1024 * 1024
MAX_BLOCK_E = 8192


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nd: int, out_dtype):
    di = pl.program_id(1)

    @pl.when(di == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # int8 tile upcasts in-register: HBM saw 1 byte/weight
    w = q_ref[...].astype(jnp.bfloat16)              # [BD, BE]
    x = x_ref[...]                                   # [B, BD]
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(di == nd - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] * s_ref[...].astype(jnp.float32)) \
            .astype(out_dtype)


def _divisor_block(n: int, quantum: int, cap: int) -> int:
    """Largest divisor of ``n`` that is a multiple of ``quantum`` and
    <= cap; falls back to halving ``cap`` when no such divisor exists
    (then requiring only divisibility of n)."""
    best = 0
    m = 1
    while quantum * m <= min(n, cap):
        if n % (quantum * m) == 0:
            best = quantum * m
        m += 1
    if best:
        return best
    blk = min(n, cap)
    while n % blk:
        blk //= 2
    return max(blk, 1)


def plan_blocks(d: int, e: int):
    """(bd, be, grid_cells) for a [D, E] weight. Callers (models/base.qdot)
    only route through the kernel when the plan is a FEW fat cells:
    per-grid-cell overhead measured ~2 us, which erases the int8 bandwidth
    win once divisor-hostile dims shatter the grid (LLaMA's 11008 = 2^8*43
    yields 256-wide blocks -> ~2000 cells/step at 6.7B, a net regression
    vs the einsum). A manual-DMA whole-matmul kernel removes the per-cell
    cost and is the round-5 path."""
    be = _divisor_block(e, 128, MAX_BLOCK_E)
    bd = _divisor_block(d, 128, max(MAX_TILE_BYTES // be, 512))
    return bd, be, (d // bd) * (e // be)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul(x: jax.Array, q: jax.Array, s: jax.Array,
                interpret: Optional[bool] = None) -> jax.Array:
    """``(x [B, D] bf16) @ (q [D, E] int8) * (s [..., E] f32) -> [B, E]``.

    ``s`` may carry leading unit dims (the engine stores per-layer scales
    as [1, E]); it is flattened to [E].
    """
    b, d = x.shape
    d2, e = q.shape
    assert d == d2, (x.shape, q.shape)
    s = s.reshape(e)
    # bd is BOTH x's last dim block (must be 128-divisible) and the weight
    # block's sublane dim — plan_blocks uses quantum 128 for either
    bd, be, _cells = plan_blocks(d, e)
    nd, ne = d // bd, e // be
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    kernel = functools.partial(_kernel, nd=nd, out_dtype=x.dtype)
    kw = {}
    if not interpret:
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid=(ne, nd),
        in_specs=[
            pl.BlockSpec((b, bd), lambda ei, di: (0, di)),
            pl.BlockSpec((bd, be), lambda ei, di: (di, ei)),
            pl.BlockSpec((1, be), lambda ei, di: (0, ei)),
        ],
        out_specs=pl.BlockSpec((b, be), lambda ei, di: (0, ei)),
        out_shape=jax.ShapeDtypeStruct((b, e), x.dtype),
        scratch_shapes=[pltpu.VMEM((b, be), jnp.float32)],
        interpret=interpret,
        **kw,
    )(x, q.astype(jnp.int8), s.reshape(1, e))
