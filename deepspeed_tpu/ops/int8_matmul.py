"""Int8 weight-streaming matmul kernel for memory-bound decode.

Reference counterpart: the dequant-fused int8 GEMV path in
``csrc/transformer/inference`` (pt_binding.cpp vector_matmul + the
dequantization kernels in dequantize.cu) — the reference streams int8
weights through a fused dequant+GEMV so HBM traffic stays 1 byte/weight.

Why a Pallas kernel: XLA will not reliably fuse an ``int8 -> bf16``
convert into a dot operand — measured at GPT-2-125M decode, the
``qdot`` einsum path (convert materialized per layer) made int8 SLOWER
than bf16 (0.53 vs 0.43 ms/tok) because each weight pays int8-read +
bf16-write + bf16-read. Here the int8 tile is DMA'd into VMEM as int8
(1 byte/weight of HBM traffic — the whole point of weight-only
quantization) and upcast in-register on its way into the MXU; the
per-output-column scale multiplies the f32 accumulator once at the end.

Decode shapes: activations are tiny ([B<=16, D]); weights dominate.
The grid walks (E tiles x D tiles) with D innermost so each output tile
accumulates across the contraction in VMEM scratch.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Fat tiles: decode matmuls are weight-streaming-bound and the per-grid-cell
# overhead is what erased the int8 bandwidth win in the first cut (~430
# cells/step at 125M measured ≈ bf16). Blocks are picked as the LARGEST
# divisors of (E, D) under a VMEM byte budget — at 125M every block matmul
# becomes 1 grid cell ([768, 2304] int8 = 1.7 MB); at 6.7B shapes ~2-8
# cells. The Pallas pipeline double-buffers every block, so an N-byte
# int8 tile costs 2N of VMEM before the f32 accumulator and activation
# blocks — budget 4 MB to stay under the ~16 MB/core VMEM.
MAX_TILE_BYTES = 4 * 1024 * 1024
MAX_BLOCK_E = 8192


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nd: int, out_dtype):
    di = pl.program_id(1)

    @pl.when(di == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # int8 tile upcasts in-register to the ACTIVATION dtype (an fp32-
    # compute serving config must not silently mix f32 x bf16 operands):
    # HBM saw 1 byte/weight either way
    w = q_ref[...].astype(x_ref.dtype)               # [BD, BE]
    x = x_ref[...]                                   # [B, BD]
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(di == nd - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] * s_ref[...].astype(jnp.float32)) \
            .astype(out_dtype)


def _divisor_block(n: int, quantum: int, cap: int) -> int:
    """Largest divisor of ``n`` that is a multiple of ``quantum`` and
    <= cap; falls back to halving ``cap`` when no such divisor exists
    (then requiring only divisibility of n)."""
    best = 0
    m = 1
    while quantum * m <= min(n, cap):
        if n % (quantum * m) == 0:
            best = quantum * m
        m += 1
    if best:
        return best
    blk = min(n, cap)
    while n % blk:
        blk //= 2
    return max(blk, 1)


def plan_blocks(d: int, e: int):
    """(bd, be, grid_cells) for a [D, E] weight. Callers (models/base.qdot)
    only route through the kernel when the plan is a FEW fat cells:
    per-grid-cell overhead measured ~2 us, which erases the int8 bandwidth
    win once divisor-hostile dims shatter the grid (LLaMA's 11008 = 2^8*43
    yields 256-wide blocks -> ~2000 cells/step at 6.7B, a net regression
    vs the einsum). The manual-DMA whole-matmul kernel
    (:func:`int8_matmul_dma`) removes the per-cell cost and is what
    production routes through now."""
    be = _divisor_block(e, 128, MAX_BLOCK_E)
    bd = _divisor_block(d, 128, max(MAX_TILE_BYTES // be, 512))
    return bd, be, (d // bd) * (e // be)


def _aligned_divisors(n):
    return [m for m in range(128, n + 1, 128) if n % m == 0]


def _hand_dma_plan(d: int, e: int, cap: int = 2_500_000):
    """Hand-picked (bd, be) divisor tiles for the manual-DMA kernel.
    Offsets/extents must align to the HBM tiling (128 on both edges
    here: the bf16 activation slice shares bd), but tiles only need to
    DIVIDE the dims — not be powers of two — so divisor-hostile dims
    still tile fat (11008 = 2^7*86). DMA throughput is set by the ROW
    length (a [bd, be] tile is bd strided rows of be bytes; be == E is
    one contiguous block — measured 8x the bandwidth of 256-byte rows),
    so maximize be FIRST, then bd under the VMEM cap."""
    best = None
    for be in _aligned_divisors(e):
        for bd in _aligned_divisors(d):
            if bd * be > cap:
                continue
            key = (be, bd)  # row length dominates; then tile size
            if best is None or key > best[0]:
                best = (key, bd, be)
    if best is None:
        # no 128-aligned divisor tiling under the cap (e.g. a dim that is
        # not a multiple of 128): callers fall back to the einsum path
        return None
    return best[1], best[2]


def _dma_plan(d: int, e: int, cap: int = 2_500_000):
    """(bd, be) tiles: the MEASURED artifact entry (ops/autotune.py,
    ISSUE 12 satellite) when one exists for this backend+shape and
    validates (128-aligned divisors of the live dims), else the
    hand-picked :func:`_hand_dma_plan`. An entry may carry either
    explicit ``bd``/``be`` tiles or just a re-tuned VMEM ``cap``."""
    from deepspeed_tpu.ops import autotune

    ent = autotune.lookup("int8_matmul_dma", autotune.matmul_key(d, e))
    if ent:
        try:
            if "bd" in ent and "be" in ent:
                bd, be = int(ent["bd"]), int(ent["be"])
                if (bd in _aligned_divisors(d)
                        and be in _aligned_divisors(e)):
                    return bd, be
            elif "cap" in ent:
                plan = _hand_dma_plan(d, e, int(ent["cap"]))
                if plan is not None:
                    return plan
        except Exception:
            pass
    return _hand_dma_plan(d, e, cap)


def _dma_kernel(layer_ref, x_ref, s_ref, w_any, o_ref, wbuf, acc_ref, sem,
                *, b, d, e, bd, be, out_dtype, stacked):
    """One invocation covers the whole [B, D] @ [D, E] int8 matmul:
    static-unrolled walk over (e-tile, d-tile) with double-buffered
    manual DMA of int8 weight tiles — no per-grid-cell dispatch cost
    (the gridded kernel's ~2 us/cell erased the int8 bandwidth win on
    divisor-hostile shapes; VERDICT r4 #2). With ``stacked``, the weight
    operand is the FULL [L, D, E] tensor and ``layer_ref`` picks the
    layer inside the DMA index: a host-side slice of an int8 custom-call
    operand materializes a full per-step copy of the weight (measured as
    round 4's '66% of streaming bound' int8 ceiling at 6.7B)."""
    nd, ne = d // bd, e // be
    order = [(ei, di) for ei in range(ne) for di in range(nd)]
    layer = layer_ref[0]

    def dma(slot, t):
        ei, di = order[t]
        src = w_any.at[layer] if stacked else w_any
        return pltpu.make_async_copy(
            src.at[pl.ds(di * bd, bd), pl.ds(ei * be, be)],
            wbuf.at[slot], sem.at[slot])

    scales = s_ref[layer] if stacked else s_ref[0]      # [E] f32
    dma(0, 0).start()
    for t, (ei, di) in enumerate(order):
        slot = t % 2
        if t + 1 < len(order):
            dma(1 - slot, t + 1).start()
        dma(slot, t).wait()
        if di == 0:
            acc_ref[...] = jnp.zeros_like(acc_ref)
        w = wbuf[slot].astype(x_ref.dtype)        # int8 -> x dtype in-register
        xs = x_ref[:, pl.ds(di * bd, bd)]
        acc_ref[...] += jax.lax.dot_general(
            xs, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if di == nd - 1:
            o_ref[:, pl.ds(ei * be, be)] = (
                acc_ref[...] * scales[None, ei * be:(ei + 1) * be].astype(
                    jnp.float32)).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "plan"))
def int8_matmul_dma(x: jax.Array, q: jax.Array, s: jax.Array,
                    layer=None, interpret: Optional[bool] = None,
                    plan: Optional[tuple] = None) -> jax.Array:
    """``(x [B, D]) @ (q [D, E] int8) * (s [..., E] f32) -> [B, E]`` as ONE
    Pallas invocation with manually-driven DMA over divisor tiles.

    ``q`` may be the FULL layer-stacked ``[L, D, E]`` tensor with
    ``layer`` a scalar index (``s`` then ``[L, 1, E]``): the kernel
    DMA-slices the layer itself, which keeps the scan body free of
    host-side int8 slices (XLA materializes a sliced custom-call operand
    as a full copy — 1.5x the weight traffic per decode step, measured
    at 6.7B).

    Reference counterpart: the fused dequant GEMM/GEMV paths in
    ``csrc/transformer/inference`` (dequantize.cu:230 + the int8 paths in
    pt_binding.cpp:1747-1806) — HBM sees 1 byte/weight, the upcast rides
    the register file. Requires D % 128 == 0 and E % 128 == 0 (int8 HBM
    tile + bf16 activation-slice alignment); ``qdot`` falls back to the
    einsum otherwise. ``plan`` (static ``(bd, be)`` tuple) overrides the
    tile plan — the autotune micro-bench harness's candidate; production
    callers leave it None and get the measured-artifact-or-hand-picked
    resolution of ``_dma_plan``.
    """
    b, d = x.shape
    stacked = q.ndim == 3
    if stacked:
        nl, d2, e = q.shape
        assert layer is not None, "stacked int8_matmul_dma needs layer"
    else:
        d2, e = q.shape
        nl = 1
    assert d == d2, (x.shape, q.shape)
    if plan is None:
        plan = _dma_plan(d, e)
    assert plan is not None, (d, e)
    bd, be = plan
    assert d % bd == 0 and e % be == 0, (plan, d, e)
    s = s.reshape(nl, e)
    layer_a = jnp.asarray(0 if layer is None else layer, jnp.int32).reshape(1)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    kernel = functools.partial(_dma_kernel, b=b, d=d, e=e, bd=bd, be=be,
                               out_dtype=x.dtype, stacked=stacked)
    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # layer
            pl.BlockSpec(memory_space=pltpu.VMEM),   # x
            pl.BlockSpec(memory_space=pltpu.VMEM),   # scales
            pl.BlockSpec(memory_space=pl.ANY),       # int8 weights (HBM)
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, e), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, bd, be), jnp.int8),       # weight tile slots
            pltpu.VMEM((b, be), jnp.float32),        # accumulator
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(layer_a, x, s.astype(jnp.float32), q.astype(jnp.int8))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul(x: jax.Array, q: jax.Array, s: jax.Array,
                interpret: Optional[bool] = None) -> jax.Array:
    """``(x [B, D] bf16) @ (q [D, E] int8) * (s [..., E] f32) -> [B, E]``.

    The GRIDDED variant — superseded in production by
    :func:`int8_matmul_dma` (qdot routes there; this one pays ~2 us per
    grid cell). Kept as the pipeline-managed formulation for comparison
    benchmarks and interpret-mode coverage.

    ``s`` may carry leading unit dims (the engine stores per-layer scales
    as [1, E]); it is flattened to [E].
    """
    b, d = x.shape
    d2, e = q.shape
    assert d == d2, (x.shape, q.shape)
    s = s.reshape(e)
    # bd is BOTH x's last dim block (must be 128-divisible) and the weight
    # block's sublane dim — plan_blocks uses quantum 128 for either
    bd, be, _cells = plan_blocks(d, e)
    nd, ne = d // bd, e // be
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    kernel = functools.partial(_kernel, nd=nd, out_dtype=x.dtype)
    kw = {}
    if not interpret:
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid=(ne, nd),
        in_specs=[
            pl.BlockSpec((b, bd), lambda ei, di: (0, di)),
            pl.BlockSpec((bd, be), lambda ei, di: (di, ei)),
            pl.BlockSpec((1, be), lambda ei, di: (0, ei)),
        ],
        out_specs=pl.BlockSpec((b, be), lambda ei, di: (0, ei)),
        out_shape=jax.ShapeDtypeStruct((b, e), x.dtype),
        scratch_shapes=[pltpu.VMEM((b, be), jnp.float32)],
        interpret=interpret,
        **kw,
    )(x, q.astype(jnp.int8), s.reshape(1, e))
