"""Rotary position embeddings.

Reference counterpart: ``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu``
(432 LoC CUDA). On TPU this is pure VPU elementwise work that XLA fuses into
the surrounding projections, so the jnp form IS the fast path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float = 10000.0):
    """Precompute cos/sin tables [T, Dh/2] in fp32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [T, Dh/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rotary_pos_emb(x: jax.Array, cos: jax.Array, sin: jax.Array,
                         position_offset=0) -> jax.Array:
    """x: [B, T, H, Dh]; cos/sin: [T_max, Dh/2] tables.

    Pairs (x[2i], x[2i+1]) rotated by position angle — the interleaved GPT-NeoX
    convention used by LLaMA.

    ``position_offset`` may be a per-slot ``[B]`` vector (continuous
    batching): each batch row is then rotated at its own position.
    """
    b, t, h, dh = x.shape
    if not isinstance(position_offset, int) and jnp.ndim(position_offset) == 1:
        pos = position_offset[:, None] + jnp.arange(t)[None, :]  # [B, T]
        c = cos[pos][:, :, None, :]  # [B, T, 1, Dh/2]
        s = sin[pos][:, :, None, :]
    else:
        if isinstance(position_offset, int) and position_offset == 0:
            c = jax.lax.dynamic_slice_in_dim(cos, 0, t, axis=0)
            s = jax.lax.dynamic_slice_in_dim(sin, 0, t, axis=0)
        else:
            c = jax.lax.dynamic_slice_in_dim(cos, position_offset, t, axis=0)
            s = jax.lax.dynamic_slice_in_dim(sin, position_offset, t, axis=0)
        c = c[None, :, None, :]  # [1, T, 1, Dh/2]
        s = s[None, :, None, :]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    out = jnp.stack([o1, o2], axis=-1).reshape(b, t, h, dh)
    return out.astype(x.dtype)
