"""Attention ops.

Reference counterpart: the fused attention kernels in
``csrc/transformer/softmax_kernels.cu`` / ``csrc/transformer/inference/csrc/softmax.cu``
(training + inference softmax with causal/alibi masking). Here the canonical
implementation is jnp (XLA fuses QK^T→mask→softmax→PV well on the MXU);
a Pallas flash-attention fast path (``flash_attention.py``) overrides it via
the op registry on real TPU backends for long sequences.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def multihead_attention(
    q: jax.Array,  # [B, T, H, Dh]
    k: jax.Array,  # [B, S, H, Dh]
    v: jax.Array,  # [B, S, H, Dh]
    *,
    causal: bool = True,
    mask: Optional[jax.Array] = None,  # [B, 1, T, S] additive or bool
    bias: Optional[jax.Array] = None,  # e.g. alibi [H, T, S]
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Reference (jnp) attention; softmax in fp32 regardless of input dtype."""
    *_, t, h, dh = q.shape
    s = k.shape[1]
    scale = scale if scale is not None else dh ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k, precision=None).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        causal_mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        if mask.dtype == bool:
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def attention_with_kv_cache(
    q: jax.Array,        # [B, 1, H, Dh] decode query (or [B, T, H, Dh] prefill)
    k_new: jax.Array,    # same T as q
    v_new: jax.Array,
    k_cache: jax.Array,  # [B, S_max, H, Dh]
    v_cache: jax.Array,
    cache_index: jax.Array,  # scalar int — tokens already in cache
    *,
    scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,  # [H, S_max] additive (alibi: softmax
    # shift-invariance makes slopes*key_pos correct for every query position)
    window: Optional[jax.Array] = None,  # scalar: keys older than
    # q_pos-window are masked (GPT-Neo local attention); None = full causal
):
    """Decode-time attention against a static-shape KV cache.

    Reference counterpart: ``softmax_context`` (csrc/transformer/inference
    pt_binding.cpp) + the inference_context.h KV workspace. Static shapes keep
    the decode loop compiled once (the CUDA-graph analog — SURVEY §7.12).
    Returns (out, k_cache, v_cache) with the new tokens written at
    ``cache_index``.
    """
    b, t, hq, dh = q.shape
    hkv = k_cache.shape[2]
    s_max = k_cache.shape[1]
    k_cache = jax.lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), (0, cache_index, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), (0, cache_index, 0, 0))
    scale = scale if scale is not None else dh ** -0.5
    # GQA: q heads grouped over kv heads (hq == hkv * rep; rep == 1 for MHA)
    rep = hq // hkv
    qg = q.reshape(b, t, hkv, rep, dh)
    logits = jnp.einsum("btkrd,bskd->bkrts", qg, k_cache).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32).reshape(
            1, hkv, rep, 1, s_max)
    # positions <= cache_index + offset are valid (causal within the new block)
    pos = jnp.arange(s_max)[None, :]  # [1, S]
    q_pos = cache_index + jnp.arange(t)[:, None]  # [T, 1]
    valid = pos <= q_pos  # [T, S]
    if window is not None:
        valid = valid & (q_pos - pos < window)
    logits = jnp.where(valid[None, None, None], logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkrts,bskd->btkrd", probs, v_cache)
    return out.reshape(b, t, hq, dh), k_cache, v_cache
