"""Attention ops.

Reference counterpart: the fused attention kernels in
``csrc/transformer/softmax_kernels.cu`` / ``csrc/transformer/inference/csrc/softmax.cu``
(training + inference softmax with causal/alibi masking). Here the canonical
implementation is jnp (XLA fuses QK^T→mask→softmax→PV well on the MXU);
a Pallas flash-attention fast path (``flash_attention.py``) overrides it via
the op registry on real TPU backends for long sequences.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

# B=1 fused-decode routing threshold: bytes of ONE layer's K cache at
# full allocated length (V doubles the actual stream; the threshold is
# calibrated in the same K-only unit). The kernel's fixed per-invocation
# cost (~28 us/call at 125M geometry, PROFILE_DECODE.md) only amortizes
# when the cache stream is fat enough: measured LOSS at 125M B=1 Dh=64
# (~1.0 MB K/layer: einsum 0.46 vs kernel 0.60 ms/tok) and WIN at 6.7B
# B=1 Dh=128 (~5.2 MB K/layer: 19.15 -> 18.25 ms/tok). 2 MB splits the
# two measured points; scripts/measure_decode.py --b1-dh128 measures the
# LLaMA geometry directly on hardware, and the env override lets that
# measurement force either path without a code change (ADVICE round 5:
# the fixed per-layer DMA overhead was never measured at B=1/Dh>=128).
_B1_FUSED_MIN_BYTES = int(os.environ.get(
    "DEEPSPEED_TPU_B1_FUSED_MIN_BYTES", 2 * 1024 * 1024))


def multihead_attention(
    q: jax.Array,  # [B, T, H, Dh]
    k: jax.Array,  # [B, S, H, Dh]
    v: jax.Array,  # [B, S, H, Dh]
    *,
    causal: bool = True,
    mask: Optional[jax.Array] = None,  # [B, 1, T, S] additive or bool
    bias: Optional[jax.Array] = None,  # e.g. alibi [H, T, S]
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Reference (jnp) attention; softmax in fp32 regardless of input dtype."""
    *_, t, h, dh = q.shape
    s = k.shape[1]
    scale = scale if scale is not None else dh ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k, precision=None).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        causal_mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        if mask.dtype == bool:
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def kv_pack_factor(head_dim: int) -> int:
    """Token-pair packing factor for the stacked KV cache. TPU HBM tiles
    bf16 buffers T(8, 128): a [.., S, Dh] cache with Dh < 128 is
    lane-PADDED to 128 in HBM (2x the footprint and stream traffic at
    Dh = 64). Packing ``pair = 128 / Dh`` adjacent tokens into one
    [.., S/pair, Dh*pair] row keeps the buffer dense and gives the fused
    decode kernel (ops/decode_step.py) 128-aligned DMA slices."""
    if head_dim >= 128 or 128 % head_dim:
        return 1
    return 128 // head_dim


def alloc_kv_cache(num_layers: int, batch: int, num_kv_heads: int,
                   max_len: int, head_dim: int, dtype, *,
                   packed: bool = True):
    """Zeros for one stacked cache tensor (call twice for K and V).
    Packed shape [L, B, H, S/pair, Dh*pair] unless ``packed=False``
    (models whose decode always needs the einsum path — ALiBi bias or
    per-layer windows — keep the plain [L, B, H, S, Dh] form). Batch-1
    caches with Dh < 128 also stay unpacked: there the fused kernel's
    fixed per-layer overhead loses to the einsum (measured 0.60 vs 0.46
    ms/tok at 125M B=1), and the allocation shape is what routes
    :func:`cached_attention`. A ``max_len`` the fused kernel can't
    stream (not 128-aligned) also stays unpacked — a packed cache the
    kernel rejects would pay the unpack view EVERY step."""
    pair = (kv_pack_factor(head_dim)
            if (packed and batch >= 2 and max_len % 128 == 0) else 1)
    assert max_len % max(pair, 1) == 0, (max_len, pair)
    return jnp.zeros((num_layers, batch, num_kv_heads, max_len // pair,
                      head_dim * pair), dtype)


def cache_seq_len(k_full, head_dim: int) -> int:
    """Max sequence length of a (possibly packed) stacked cache."""
    return k_full.shape[3] * (k_full.shape[4] // head_dim)


def cached_attention(q, k_full, v_full, k_new, v_new, layer, idx, *,
                     scale=None, bias=None, window=None, block_table=None):
    """One cached-attention layer step: write the new block's K/V into the
    full stacked [L, B, Hkv, S, Dh] caches (possibly token-pair packed,
    see :func:`kv_pack_factor`), attend, return ``(attn, k_full, v_full)``.

    ``idx`` is the first free cache position: a scalar for the uniform
    batch-decode path, or a PER-SLOT ``[B]`` vector for the continuous-
    batching serving runtime (serving/engine.py) — each batch row then
    writes at and attends over ITS OWN valid prefix.

    Single-token decode on TPU routes to the fused Pallas step
    (ops/decode_step.py): the kernel owns BOTH the cache write and the
    streaming read, so XLA keeps the decode loop's cache carry in the
    default streaming-friendly layout instead of the einsum-oriented one
    a ``dynamic_update_slice`` write anchors (round-4 root cause of
    batch-8 decode at half its roofline — PROFILE_DECODE.md). Everything
    else (prefill blocks, ALiBi bias, sliding windows, CPU) takes the
    einsum path, view-unpacking packed caches first.

    ``block_table`` switches to the BLOCK-PAGED addressing mode (ISSUE 6,
    serving/kv_blocks.py): ``k_full``/``v_full`` are then a global block
    POOL ``[L, N_blocks, Hkv, bs(/pair), Dh(*pair)]`` and each batch
    row's KV lives in the blocks named by its ``block_table[b]`` row —
    logical token position p maps to pool block ``table[b, p // bs]``,
    row ``p % bs``. ``idx`` must be the per-slot [B] length vector. The
    table is TRACED DATA (int32 [B, max_blocks]), never a shape: one
    compiled program serves every block assignment, which is what lets
    the radix prefix cache remap blocks between steps without a single
    recompile.

    A QUANTIZED pool (ISSUE 12, serving/kv_quant.py) arrives as a
    ``{"q": payload, "s": scales}`` pytree in place of each cache
    array — block-paged only (the write path quantizes on store, the
    read paths dequantize in-register; the models carry the tree
    opaquely, so one code path serves every kv_dtype)."""
    if block_table is not None:
        return _block_cached_attention(q, k_full, v_full, k_new, v_new,
                                       layer, idx, block_table,
                                       scale=scale, bias=bias,
                                       window=window)
    if isinstance(k_full, dict):
        raise ValueError(
            "quantized KV pools are block-paged only: cached_attention "
            "got a {'q','s'} cache without a block_table (serving must "
            "run with prefix_cache=True to use kv_dtype)")
    b, t = q.shape[0], q.shape[1]
    dh = q.shape[3]
    pair = k_full.shape[4] // dh
    if (t == 1 and bias is None and window is None
            and jax.default_backend() == "tpu"
            # the allocation shape routes: an unpacked Dh<128 cache means
            # alloc_kv_cache decided the einsum path wins (batch 1)
            and pair == kv_pack_factor(dh)
            # B=1 with a thin per-layer cache stream: the kernel's fixed
            # per-invocation cost loses to the einsum (see
            # _B1_FUSED_MIN_BYTES above; only Dh>=128 geometries reach
            # this — Dh<128 B=1 is already routed by allocation shape)
            and (b >= 2 or k_full.shape[2] * k_full.shape[3] * k_full.shape[4]
                 * jnp.dtype(k_full.dtype).itemsize >= _B1_FUSED_MIN_BYTES)):
        from deepspeed_tpu.ops.decode_step import fused_decode_step, supports

        if supports(q.shape[2], k_full.shape[2],
                    k_full.shape[3] * pair, dh):
            return fused_decode_step(q, k_full, v_full, k_new, v_new,
                                     layer, idx, scale=scale)
    if pair > 1:  # unpack for the einsum path (free on CPU; prefill-only
        # on TPU, where the repack copy is once per generate, not per step)
        l, b, hkv, sp, dhp = k_full.shape
        shape = (l, b, hkv, sp * pair, dh)
        ku, vu, kl, vl = write_kv_cache(
            k_full.reshape(shape), v_full.reshape(shape), k_new, v_new,
            layer, idx)
        attn = decode_attention(q, kl, vl, idx, scale=scale, bias=bias,
                                window=window)
        return attn, ku.reshape(k_full.shape), vu.reshape(v_full.shape)
    k_full, v_full, kl, vl = write_kv_cache(k_full, v_full, k_new, v_new,
                                            layer, idx)
    attn = decode_attention(q, kl, vl, idx, scale=scale, bias=bias,
                            window=window)
    return attn, k_full, v_full


def write_kv_cache(k_full, v_full, k_new, v_new, layer, idx):
    """Write one block's new K/V ([B, T, Hkv, Dh]) into the full stacked
    head-major [L, B, Hkv, S, Dh] caches at (layer, idx) — the per-token
    slice write that XLA keeps in place on the layer-scan carry. Returns
    (k_full, v_full, k_layer, v_layer) with the per-layer [B, Hkv, S, Dh]
    views ready for :func:`decode_attention`.

    A per-slot ``[B]`` idx vector (continuous batching) scatters each
    row's block at its own position instead of one shared slice start:
    row b's token j lands at cache position ``idx[b] + j``. T > 1 is the
    speculative-decoding verify path (serving/speculative.py) — all
    ``k + 1`` candidate tokens' K/V are written in one pass, and entries
    past the accepted prefix stay dead behind the per-slot length vector
    (rollback-by-masking, no copies). ``mode="drop"`` makes any position
    past the allocation a silent no-op instead of undefined behavior
    (inactive slots carry stale lengths; their masked garbage writes must
    never land out of bounds)."""
    if jnp.ndim(idx) == 1:
        b, t = k_new.shape[0], k_new.shape[1]
        rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
        pos = idx[:, None] + jnp.arange(t)[None, :]              # [B, T]
        k_full = k_full.at[layer, rows, :, pos, :].set(
            k_new.astype(k_full.dtype), mode="drop")
        v_full = v_full.at[layer, rows, :, pos, :].set(
            v_new.astype(v_full.dtype), mode="drop")
    else:
        k_full = jax.lax.dynamic_update_slice(
            k_full, k_new.transpose(0, 2, 1, 3)[None].astype(k_full.dtype),
            (layer, 0, 0, idx, 0))
        v_full = jax.lax.dynamic_update_slice(
            v_full, v_new.transpose(0, 2, 1, 3)[None].astype(v_full.dtype),
            (layer, 0, 0, idx, 0))
    return (k_full, v_full,
            jax.lax.dynamic_index_in_dim(k_full, layer, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(v_full, layer, 0, keepdims=False))


def write_slot_prefix(k_full, v_full, k_pref, v_pref, slot):
    """Insert a prefilled single-sequence prefix cache into slot ``slot``
    of the persistent slot-paged caches (serving/kv_slots.py).

    k_pref/v_pref: [L, 1, Hkv, T_bucket, Dh] UNPACKED prefix caches from a
    batch-1 bucket prefill (alloc_kv_cache never packs batch 1).
    k_full/v_full: [L, B, Hkv, S/pair, Dh*pair] possibly packed persistent
    caches. The bucket rows are viewed in the persistent pack factor (a
    free bitcast — requires T_bucket % pair == 0) and written with ONE
    dynamic_update_slice at batch position ``slot``, row 0. Rows past the
    request's true length hold pad-token garbage; the per-slot length
    vector masks them until the decode loop overwrites them one by one."""
    l, one, hkv, t_b, dh = k_pref.shape
    assert one == 1, "slot insert takes a single-sequence prefix cache"
    pair = k_full.shape[4] // dh
    if pair > 1:
        assert t_b % pair == 0, (t_b, pair)
        k_pref = k_pref.reshape(l, 1, hkv, t_b // pair, dh * pair)
        v_pref = v_pref.reshape(l, 1, hkv, t_b // pair, dh * pair)
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    k_full = jax.lax.dynamic_update_slice(
        k_full, k_pref.astype(k_full.dtype), (zero, slot, zero, zero, zero))
    v_full = jax.lax.dynamic_update_slice(
        v_full, v_pref.astype(v_full.dtype), (zero, slot, zero, zero, zero))
    return k_full, v_full


def extract_slot_kv(k_full, v_full, slot):
    """Slice slot ``slot``'s row pair out of the slot-paged caches as a
    batch-1 stacked cache ``[L, 1, Hkv, S(/pair), Dh(*pair)]`` in the
    persistent pack factor. Two callers (ISSUE 8):

      * the chunked-prefill program steps the sliced row as a batch-1
        cache (the chunk's queries attend over the slot's own
        already-prefilled prefix) and writes it back;
      * preemption swap-out hands the row to the host swap buffer.

    ``slot`` is a traced scalar — one compiled program serves every
    slot."""
    slot = jnp.asarray(slot, jnp.int32)
    return (jax.lax.dynamic_slice_in_dim(k_full, slot, 1, 1),
            jax.lax.dynamic_slice_in_dim(v_full, slot, 1, 1))


def insert_slot_kv(k_full, v_full, k_row, v_row, slot):
    """Write a batch-1 row pair (the persistent pack factor — exactly
    what :func:`extract_slot_kv` produced) back into slot ``slot`` of
    the slot-paged caches: the chunk-prefill write-back and the
    preemption swap-in (ISSUE 8). One ``dynamic_update_slice`` per
    cache, traced slot."""
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    starts = (zero, slot, zero, zero, zero)
    return (jax.lax.dynamic_update_slice(
                k_full, k_row.astype(k_full.dtype), starts),
            jax.lax.dynamic_update_slice(
                v_full, v_row.astype(v_full.dtype), starts))


def gather_pool_blocks(k_pool, v_pool, table):
    """Gather one slot's table-named block CONTENTS
    ``[L, MB, Hkv, bs(/pair), Dh(*pair)]`` out of the block pool — the
    device half of preemption swap-OUT (ISSUE 8): the engine
    device_gets the result into the host swap buffer before freeing the
    blocks. Sentinel table entries gather the pool's garbage row
    (finite junk the restore never uploads). ``table`` is traced int32
    ``[MB]`` — one compiled program serves every block assignment.
    Quantized ``{"q", "s"}`` pools gather payloads AND scales (both are
    block-major on axis 1), so the host copy round-trips the exact
    stored bytes — which is also why quantized swap halves the host
    transfer."""
    def g(leaf):
        return jnp.take(leaf, table, axis=1, mode="clip")

    return (jax.tree_util.tree_map(g, k_pool),
            jax.tree_util.tree_map(g, v_pool))


def scatter_pool_blocks(k_pool, v_pool, k_blocks, v_blocks, dst):
    """Scatter ``[L, MB, ...]`` block contents into the pool rows named
    by ``dst`` — preemption swap-IN (ISSUE 8). Entries the restore must
    SKIP (radix re-matched shared blocks, never-written tail blocks)
    point at the pool's garbage row: their writes land where nobody
    reads, so the program's shapes never vary with how much actually
    needs uploading (duplicate garbage-row writes race only against
    each other). Quantized pools scatter payloads and scales leaf-wise
    — host bytes land back bit-identically (no requantization on a
    swap round trip; pinned by tests)."""
    def s(pool_leaf, blk_leaf):
        return pool_leaf.at[:, dst].set(blk_leaf.astype(pool_leaf.dtype),
                                        mode="drop")

    return (jax.tree_util.tree_map(s, k_pool, k_blocks),
            jax.tree_util.tree_map(s, v_pool, v_blocks))


def pool_block_size(k_pool, head_dim: int) -> int:
    """Tokens per block of a (possibly token-pair packed, possibly
    quantized) KV block pool ``[L, N, Hkv, bs/pair, Dh*pair]``."""
    from deepspeed_tpu.serving.kv_quant import pool_payload

    p = pool_payload(k_pool)
    return p.shape[3] * (p.shape[4] // head_dim)


def write_kv_blocks(k_pool, v_pool, k_new, v_new, layer, idx, block_table):
    """Scatter one step's new K/V ([B, T, Hkv, Dh]) into the UNPACKED
    block pool ``[L, N+1, Hkv, bs, Dh]`` through the per-slot block
    table: row b's token j lands at logical position ``idx[b] + j``,
    i.e. pool block ``block_table[b, pos // bs]``, row ``pos % bs``.

    Sentinel semantics (serving/kv_blocks.py): the pool's LAST physical
    row is a permanent garbage block that is never allocated — the
    engine parks freed/unallocated table entries there, and logical
    overflow past the table width routes there too. Inactive slots
    carry stale lengths and sentinel tables, and their masked writes
    must never corrupt a live block — with prefix sharing a stale table
    entry may meanwhile be pinned by another request, so the garbage
    row is a correctness requirement, not a nicety (and it lets the
    fused Pallas block kernel skip per-row write predication
    entirely).

    Quantized pools (ISSUE 12): ``k_pool``/``v_pool`` may be the
    ``{"q", "s"}`` pytree with an UNPACKED payload view
    ``[L, N+1, Hkv, bs, Dh]`` — this is the quantize-on-store seam:
    each new token's symmetric per-head scale is computed HERE
    (serving/kv_quant.kv_quantize), its payload scatters exactly like
    the unquantized write, and the scale scatters into the pair-grouped
    scale array at ``[layer, block, :, pos % pair, (pos % bs) // pair]``."""
    if isinstance(k_pool, dict):
        from deepspeed_tpu.serving.kv_quant import kv_quantize

        kq_pool, ks_pool = k_pool["q"], k_pool["s"]
        vq_pool, vs_pool = v_pool["q"], v_pool["s"]
        kv_dtype = "int8" if kq_pool.dtype == jnp.int8 else "fp8"
        n_phys, bs = kq_pool.shape[1], kq_pool.shape[3]
        pair = ks_pool.shape[3]
        b, t = k_new.shape[0], k_new.shape[1]
        mb = block_table.shape[1]
        pos = idx[:, None] + jnp.arange(t)[None, :]              # [B, T]
        jb = pos // bs
        pb = jnp.take_along_axis(block_table, jnp.clip(jb, 0, mb - 1),
                                 axis=1)
        pb = jnp.where(jb < mb, pb, n_phys - 1)
        wi = pos % bs
        half, row = wi % pair, wi // pair        # pair-grouped scale idx
        kq, ks = kv_quantize(k_new, kv_dtype)    # [B,T,Hkv,Dh], [B,T,Hkv]
        vq, vs = kv_quantize(v_new, kv_dtype)
        k_pool = {"q": kq_pool.at[layer, pb, :, wi, :].set(kq, mode="drop"),
                  "s": ks_pool.at[layer, pb, :, half, row].set(
                      ks, mode="drop")}
        v_pool = {"q": vq_pool.at[layer, pb, :, wi, :].set(vq, mode="drop"),
                  "s": vs_pool.at[layer, pb, :, half, row].set(
                      vs, mode="drop")}
        return k_pool, v_pool
    n_phys, bs = k_pool.shape[1], k_pool.shape[3]
    b, t = k_new.shape[0], k_new.shape[1]
    mb = block_table.shape[1]
    pos = idx[:, None] + jnp.arange(t)[None, :]                  # [B, T]
    jb = pos // bs
    pb = jnp.take_along_axis(block_table, jnp.clip(jb, 0, mb - 1), axis=1)
    pb = jnp.where(jb < mb, pb, n_phys - 1)  # overflow -> garbage row
    wi = pos % bs
    k_pool = k_pool.at[layer, pb, :, wi, :].set(
        k_new.astype(k_pool.dtype), mode="drop")
    v_pool = v_pool.at[layer, pb, :, wi, :].set(
        v_new.astype(v_pool.dtype), mode="drop")
    return k_pool, v_pool


def gather_block_kv(pool_layer, block_table, out_dtype=None):
    """Per-layer slot view of the block pool: gather each row's blocks
    ``[N+1, Hkv, bs, Dh] -> [B, Hkv, MB * bs, Dh]`` (the shape
    :func:`decode_attention` expects). Sentinel table entries read the
    garbage row — garbage, but FINITE (a fill-value NaN would poison
    the PV einsum through the masked positions' 0 * NaN), and always
    dead behind the per-slot length mask; ``mode="clip"`` keeps even a
    corrupt table in range.

    A quantized ``{"q", "s"}`` layer gathers payload AND scales, then
    dequantizes into ``out_dtype`` (required for quantized layers —
    callers pass the query dtype); garbage-row reads dequantize to
    finite junk exactly like the unquantized pool's (zero at
    allocation, arbitrary once inactive slots' masked writes land
    there — always dead behind the length mask either way)."""
    if isinstance(pool_layer, dict):
        from deepspeed_tpu.serving.kv_quant import (kv_dequantize,
                                                    scales_token_order)

        assert out_dtype is not None, \
            "gather_block_kv on a quantized layer needs out_dtype"
        ql, sl = pool_layer["q"], pool_layer["s"]    # [N,Hkv,bs,Dh] /
        n, hkv, bs, dh = ql.shape                    # [N,Hkv,pair,bs/pair]
        b, mb = block_table.shape
        kb = jnp.take(ql, block_table, axis=0, mode="clip")
        sb = scales_token_order(
            jnp.take(sl, block_table, axis=0, mode="clip"))  # [B,MB,Hkv,bs]
        kb = kb.transpose(0, 2, 1, 3, 4).reshape(b, hkv, mb * bs, dh)
        sb = sb.transpose(0, 2, 1, 3).reshape(b, hkv, mb * bs)
        return kv_dequantize(kb, sb, out_dtype)
    n, hkv, bs, dh = pool_layer.shape
    b, mb = block_table.shape
    kb = jnp.take(pool_layer, block_table, axis=0, mode="clip")
    return kb.transpose(0, 2, 1, 3, 4).reshape(b, hkv, mb * bs, dh)


def _block_cached_attention(q, k_pool, v_pool, k_new, v_new, layer, idx,
                            block_table, *, scale=None, bias=None,
                            window=None):
    """Block-paged cached attention (see :func:`cached_attention`): write
    the new tokens' K/V through the block table, then attend each row
    over its own gathered block chain. Single-token decode on TPU routes
    to the fused Pallas block-table step (ops/decode_step.py) — the
    kernel streams each slot's valid blocks straight from the pool, so
    paging costs no extra HBM copy; everything else (suffix prefill,
    speculative verify blocks, CPU) takes the gather + einsum path.

    Quantized pools (ISSUE 12): same two routes — the fused kernel
    streams int8/fp8 payload chunks and dequantizes in-register (half
    the HBM bytes per chunk), the einsum path writes through the
    quantizing :func:`write_kv_blocks` and reads through the
    dequantizing :func:`gather_block_kv`. Both attend over the
    quantize->dequantize image of the NEW token too (the value future
    steps will read), so kernel and einsum outputs agree across
    backends."""
    quant = isinstance(k_pool, dict)
    kq_arr = k_pool["q"] if quant else k_pool
    b, t = q.shape[0], q.shape[1]
    dh = q.shape[3]
    l, n, hkv, bsp, dhp = kq_arr.shape
    pair = dhp // dh
    bs = bsp * pair
    assert jnp.ndim(idx) == 1, \
        "block-paged attention needs the per-slot length vector"
    if (t == 1 and bias is None and window is None
            and jax.default_backend() == "tpu"
            and pair == kv_pack_factor(dh)):
        from deepspeed_tpu.ops.decode_step import (fused_block_decode_step,
                                                   supports_block)

        if supports_block(q.shape[2], hkv, bs, dh):
            return fused_block_decode_step(q, k_pool, v_pool, k_new, v_new,
                                           layer, idx, block_table,
                                           scale=scale)
    shape = (l, n, hkv, bs, dh)
    if quant:
        ku = {"q": k_pool["q"].reshape(shape) if pair > 1 else k_pool["q"],
              "s": k_pool["s"]}
        vu = {"q": v_pool["q"].reshape(shape) if pair > 1 else v_pool["q"],
              "s": v_pool["s"]}
    else:
        ku = k_pool.reshape(shape) if pair > 1 else k_pool
        vu = v_pool.reshape(shape) if pair > 1 else v_pool
    ku, vu = write_kv_blocks(ku, vu, k_new, v_new, layer, idx, block_table)

    def at_layer(tree):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, layer, 0,
                                                   keepdims=False), tree)

    kl, vl = at_layer(ku), at_layer(vu)
    attn = decode_attention(
        q, gather_block_kv(kl, block_table, q.dtype),
        gather_block_kv(vl, block_table, q.dtype), idx,
        scale=scale, bias=bias, window=window)
    if quant:
        return (attn,
                {"q": ku["q"].reshape(k_pool["q"].shape), "s": ku["s"]},
                {"q": vu["q"].reshape(v_pool["q"].shape), "s": vu["s"]})
    return attn, ku.reshape(k_pool.shape), vu.reshape(v_pool.shape)


def decode_attention(
    q: jax.Array,        # [B, T, Hq, Dh] current block's queries
    k_cache: jax.Array,  # [B, Hkv, S_max, Dh] — new keys ALREADY written
    v_cache: jax.Array,  # [B, Hkv, S_max, Dh]
    cache_index: jax.Array,  # scalar int — first position of q in the cache
    #                          (or per-slot [B] vector, continuous batching)
    *,
    scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,    # [H, S_max] additive (alibi)
    window: Optional[jax.Array] = None,  # scalar sliding-window size
) -> jax.Array:
    """Attention of q against a cache that already holds its keys/values.

    Reference counterpart: ``softmax_context`` (csrc/transformer/inference
    pt_binding.cpp) + the inference_context.h KV workspace. Static shapes
    keep the decode loop compiled once (the CUDA-graph analog — SURVEY
    §7.12). The write side (dynamic_update_slice of the new token's K/V at
    ``cache_index``) lives with the cache owner — models write into the full
    stacked [L, B, H, S, Dh] cache carried through the layer scan, which XLA
    updates in place; returning per-layer cache copies through scan ys
    rewrote the entire cache every decode step (round-2 weak #2, ~4x the
    weight-streaming roofline cost at batch 8).

    The cache is stored HEAD-MAJOR ([B, H, S, Dh]): each head's [S, Dh]
    K/V block is then contiguous in HBM, so the QK^T (contract Dh) and PV
    (contract S) reads stream sequentially. With the torch-style
    [B, S, Hkv, Dh] logical shape, XLA assigned the loop-carried cache a
    token-major layout (optimal for the one-token write, 128-byte-strided
    for every read): measured ~150 GB/s effective cache streaming vs
    1.6 TB/s on weights at batch 8.

    Single-token unbiased/unwindowed decode on TPU routes to the Pallas
    flash-decode kernel (ops/flash_decode.py): valid-prefix cache reads
    via scalar-prefetch block clamping + VMEM online softmax."""
    b, t, hq, dh = q.shape
    rep_ = hq // k_cache.shape[1]
    per_slot = jnp.ndim(cache_index) == 1
    if (t == 1 and bias is None and window is None and not per_slot
            and k_cache.shape[2] % 128 == 0
            and rep_ >= 8
            and jax.default_backend() == "tpu"):
        # Wide-GQA only (rep >= 8): each grid cell feeds the MXU a
        # [rep, Dh] x [Dh, BS] slab. For MHA both kernel variants MEASURED
        # SLOWER than this einsum (round 4, 125M B=8: einsum 1.42 ms/tok
        # vs 5.05 MXU-cell kernel / 1.94 head-batched VPU kernel): XLA
        # lays the decode loop's cache carry out for einsum lane
        # parallelism, and a pallas operand in that layout pays a
        # relayout copy per step — see PROFILE_DECODE.md. Cache length
        # must tile (the engine pads its KV allocation to 128).
        from deepspeed_tpu.ops.flash_decode import flash_decode

        return flash_decode(q, k_cache, v_cache, cache_index, scale=scale)
    hkv = k_cache.shape[1]
    s_max = k_cache.shape[2]
    scale = scale if scale is not None else dh ** -0.5
    # GQA: q heads grouped over kv heads (hq == hkv * rep; rep == 1 for MHA)
    rep = hq // hkv
    qg = q.reshape(b, t, hkv, rep, dh)
    logits = jnp.einsum("btkrd,bksd->bkrts", qg, k_cache).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32).reshape(
            1, hkv, rep, 1, s_max)
    # positions <= cache_index + offset are valid (causal within the new block)
    if per_slot:
        # continuous batching: each slot's own valid-prefix mask
        pos = jnp.arange(s_max)[None, None, :]                   # [1, 1, S]
        q_pos = cache_index[:, None, None] + \
            jnp.arange(t)[None, :, None]                         # [B, T, 1]
        valid = pos <= q_pos                                     # [B, T, S]
        if window is not None:
            valid = valid & (q_pos - pos < window)
        logits = jnp.where(valid[:, None, None], logits,
                           jnp.finfo(jnp.float32).min)
    else:
        pos = jnp.arange(s_max)[None, :]  # [1, S]
        q_pos = cache_index + jnp.arange(t)[:, None]  # [T, 1]
        valid = pos <= q_pos  # [T, S]
        if window is not None:
            valid = valid & (q_pos - pos < window)
        logits = jnp.where(valid[None, None, None], logits,
                           jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkrts,bksd->btkrd", probs, v_cache)
    return out.reshape(b, t, hq, dh)
