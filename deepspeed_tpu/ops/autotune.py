"""Measured kernel-plan store for the Pallas serving kernels (ISSUE 12
satellite; VERDICT next-round #4).

The fused decode kernels and the int8 weight-streaming matmul each carry
hand-picked plan constants — ``(bg, cs, vmem_limit)`` batch-group /
chunk sizing in ops/decode_step.py, ``(bd, be, cap)`` divisor tiles in
ops/int8_matmul.py — that were calibrated on one chip generation at one
model size. This module makes MEASURED plans the primary source: a
micro-bench harness (scripts/autotune_kernels.py) times candidate plans
per shape on the actual backend and writes a committed artifact
(``AUTOTUNE_KERNELS_MEASURED.json`` at the repo root, the
AUTOTUNE_125M_MEASURED.json idiom); the kernels consult
:func:`lookup` at trace time and fall back to the hand-picked constants
when no valid entry exists.

Safety rails:

  * entries apply only when the artifact's ``backend`` matches the
    running ``jax.default_backend()`` — a CPU-smoke artifact must never
    re-plan kernels on a real TPU (and vice versa);
  * every consumer re-validates an entry's divisibility/alignment
    against the live shape and silently falls back on mismatch — a
    stale or hand-edited artifact can cost performance, never
    correctness;
  * lookups happen at TRACE time only (plans are compile-time
    constants), so the artifact read is paid once per program, never on
    the serving hot path.

Artifact schema::

    {"metric": "kernel_plan_autotune",
     "backend": "cpu" | "tpu",
     "plans": {
       "decode_step":       {"<key>": {"bg", "cs", "vmem_mb", "mha",
                                       "us", "hand_us", ...}},
       "block_decode_step": {"<key>": {"mha", "vmem_mb", ...}},
       "int8_matmul_dma":   {"<key>": {"bd", "be", "cap", ...}}}}

``us`` is the chosen plan's measured per-call microseconds and
``hand_us`` the hand-picked plan's in the same windows — the harness
always includes the hand-picked plan in the candidate set and picks the
argmin, so a committed plan beats-or-ties the constants BY CONSTRUCTION
in its own measurement.
"""

from __future__ import annotations

import json
import os
from typing import Optional

ENV_PATH = "DSTPU_KERNEL_PLANS"   # artifact path override; "" disables

# ---------------------------------------------------------- VMEM budget
# Per-generation VMEM capacity table, shared between the kernels'
# scoped-limit plumbing and the `vmem-budget` lint pass (ISSUE 15): a
# committed kernel plan that cannot fit fails the LINT instead of the
# first TPU run.  Every shipped generation exposes ~16 MB of VMEM per
# core by default; Mosaic's scoped limit (vmem_limit_bytes) can be
# raised for kernels that manage their own residency — decode_step runs
# at 40 MB — but never past SCOPED_VMEM_MAX_MB, which is also the clamp
# `_entry_vmem_mha` applies to artifact entries.
DEFAULT_VMEM_MB = 16
SCOPED_VMEM_MAX_MB = 128

_REPO_ARTIFACT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir, os.pardir,
    "AUTOTUNE_KERNELS_MEASURED.json")

_UNSET = object()
_artifact = _UNSET


# ------------------------------------------------------------------- keys
def decode_key(b: int, hkv: int, s_max: int, dh: int, itemsize: int) -> str:
    """Shape key of one fused_decode_step geometry (slot-paged)."""
    return f"b{b}_hkv{hkv}_s{s_max}_dh{dh}_e{itemsize}"


def block_decode_key(b: int, hkv: int, bs: int, dh: int,
                     itemsize: int) -> str:
    """Shape key of one fused_block_decode_step geometry (block-paged;
    ``itemsize`` is the PAYLOAD's — 1 for int8/fp8 pools)."""
    return f"b{b}_hkv{hkv}_bs{bs}_dh{dh}_e{itemsize}"


def matmul_key(d: int, e: int) -> str:
    """Shape key of one int8_matmul_dma [D, E] weight geometry."""
    return f"d{d}_e{e}"


# ------------------------------------------------------------------ store
def artifact_path() -> str:
    return os.environ.get(ENV_PATH, _REPO_ARTIFACT)


def _load():
    global _artifact
    if _artifact is not _UNSET:
        return _artifact
    path = artifact_path()
    art = None
    if path:
        try:
            with open(path) as f:
                d = json.load(f)
            if isinstance(d, dict) and isinstance(d.get("plans"), dict):
                art = d
        except Exception:
            art = None
    _artifact = art
    return art


def reload() -> None:
    """Drop the memoized artifact (tests point ``DSTPU_KERNEL_PLANS``
    at scratch files; production never needs this)."""
    global _artifact
    _artifact = _UNSET


def lookup(kind: str, key: str) -> Optional[dict]:
    """Measured plan entry for ``(kind, key)`` on the CURRENT backend,
    or None (→ the caller's hand-picked constants). Consumers must
    re-validate fields against the live shape before use."""
    art = _load()
    if art is None:
        return None
    import jax

    if art.get("backend") != jax.default_backend():
        return None
    ent = art.get("plans", {}).get(kind, {}).get(key)
    return ent if isinstance(ent, dict) else None
