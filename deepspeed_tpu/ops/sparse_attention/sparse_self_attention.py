"""SparseSelfAttention module (reference
``ops/sparse_attention/sparse_self_attention.py:12``): holds a
SparsityConfig, builds/caches the block layout per sequence length, and
applies the block-sparse attention kernel.  Also carries the
``pad_to_block_size`` helper from the reference's SparseAttentionUtils so
HF-style inputs with ragged lengths can use block kernels.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.block_sparse import (
    block_sparse_attention,
)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig,
    SparsityConfig,
)


class SparseSelfAttention:
    def __init__(self, sparsity_config: Optional[SparsityConfig] = None):
        # the reference's key_padding_mask/attn_mask modes are not carried:
        # padding here is handled structurally (pad_to_block_size + layouts),
        # which keeps the kernel mask-free and static
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self._layouts: Dict[int, np.ndarray] = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, *, causal: Optional[bool] = None,
                 scale: Optional[float] = None):
        """query/key/value: [B, T, H, Dh] → [B, T, H, Dh]."""
        t = query.shape[1]
        layout = self.get_layout(t)
        if causal is None:
            causal = getattr(self.sparsity_config, "attention",
                             "bidirectional") == "unidirectional"
        return block_sparse_attention(
            query, key, value, layout, block=self.sparsity_config.block,
            causal=causal, scale=scale)

    @staticmethod
    def pad_to_block_size(block: int, input_ids, pad_token_id: int,
                          attention_mask=None):
        """Pad the sequence dim up to a block multiple (reference
        SparseAttentionUtils.pad_to_block_size). Returns (pad_len, padded
        ids, padded mask)."""
        t = input_ids.shape[1]
        pad = (-t) % block
        if pad == 0:
            return 0, input_ids, attention_mask
        ids = jnp.pad(input_ids, ((0, 0), (0, pad)),
                      constant_values=pad_token_id)
        mask = None
        if attention_mask is not None:
            mask = jnp.pad(attention_mask, ((0, 0), (0, pad)),
                           constant_values=0)
        return pad, ids, mask

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        if pad_len == 0:
            return sequence_output
        return sequence_output[:, :-pad_len]
