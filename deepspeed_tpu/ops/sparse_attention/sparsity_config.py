"""Sparsity structure configs → block-level attention layouts.

Reference analog: ``deepspeed/ops/sparse_attention/sparsity_config.py``
(SparsityConfig:10 and subclasses Fixed:95, Variable:265, BigBird:438,
BSLongformer:532, LocalSlidingWindow:632 — line refs into the reference
file).  Each config emits a boolean block layout ``[num_heads, nb, nb]``
(nb = seq_len // block) marking which [block × block] tiles of the
attention matrix are computed.  The layouts are static numpy — they key the
Pallas kernel's look-up tables at trace time, so sparsity never introduces
dynamic shapes into the compiled program.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: dense layout (reference SparsityConfig.setup_layout builds the
    all-zero layout; subclasses set blocks)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} must be divisible by "
                             f"block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), dtype=bool)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[...] = True
        return layout

    def check_and_propagate_first_head_layout(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0:1]
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active (reference DenseSparsityConfig)."""


class FixedSparsityConfig(SparsityConfig):
    """Fixed local+global pattern (reference FixedSparsityConfig:95):
    each query block attends to its local window of ``num_local_blocks``
    and to ``num_global_blocks`` global summary blocks chosen per head from
    the end of each local window (unidirectional) — with optional
    horizontal global attention for bidirectional models."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError("num_local_blocks must be divisible by "
                             "num_global_blocks")
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention '{attention}'")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires "
                             "bidirectional attention")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("different global patterns require "
                             "different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError("num_different_global_patterns is limited by "
                             "num_local_blocks // num_global_blocks")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for h in range(self.num_heads):
            # local windows
            for start in range(0, nb, self.num_local_blocks):
                end = min(start + self.num_local_blocks, nb)
                for q in range(start, end):
                    hi = (q + 1) if self.attention == "unidirectional" else end
                    layout[h, q, start:hi] = True
            # global blocks: head (or first) pattern picks which slot of the
            # local window acts as global summary
            pattern = h % self.num_different_global_patterns \
                if self.different_layout_per_head else 0
            first_global = self.num_local_blocks - \
                (pattern + 1) * self.num_global_blocks
            for wstart in range(0, nb, self.num_local_blocks):
                g0 = wstart + first_global
                g1 = g0 + self.num_global_blocks
                if g1 > nb:
                    continue
                # vertical: every later query block sees the globals
                qlo = wstart if self.attention == "bidirectional" else g1
                layout[h, qlo:, g0:g1] = True
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones_like(layout[0]))[None]
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local windows + explicit global blocks + random blocks
    (reference VariableSparsityConfig:265)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        if self.global_block_end_indices is not None and \
                len(self.global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global_block_end_indices must pair with "
                             "global_block_indices")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        rng = np.random.RandomState(self.seed)
        for h in range(self.num_heads):
            # local variable-size windows, cycling the provided sizes
            start = 0
            w = 0
            while start < nb:
                size = self.local_window_blocks[
                    min(w, len(self.local_window_blocks) - 1)]
                end = min(start + size, nb)
                for q in range(start, end):
                    hi = (q + 1) if self.attention == "unidirectional" else end
                    layout[h, q, start:hi] = True
                start, w = end, w + 1
            # globals
            for i, g in enumerate(self.global_block_indices):
                if g >= nb:
                    continue
                g1 = min(self.global_block_end_indices[i],
                         nb) if self.global_block_end_indices else g + 1
                qlo = 0 if self.attention == "bidirectional" else g1
                layout[h, qlo:, g:g1] = True
                if self.horizontal_global_attention:
                    layout[h, g:g1, :] = True
            # random blocks
            for q in range(nb):
                for g in rng.choice(nb, size=self.num_random_blocks,
                                    replace=False) if self.num_random_blocks else []:
                    layout[h, q, g] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones_like(layout[0]))[None]
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global (reference
    BigBirdSparsityConfig:438)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, attention: str = "bidirectional",
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        rng = np.random.RandomState(self.seed)
        for h in range(self.num_heads):
            for q in range(nb):
                layout[h, q, max(0, q - w):min(nb, q + w + 1)] = True  # window
                rand = rng.choice(nb, size=min(self.num_random_blocks, nb),
                                  replace=False)
                layout[h, q, rand] = True                              # random
            g = min(self.num_global_blocks, nb)
            layout[h, :, :g] = True                                    # global cols
            layout[h, :g, :] = True                                    # global rows
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones_like(layout[0]))[None]
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + chosen global blocks
    (reference BSLongformerSparsityConfig:532)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_heads):
            for q in range(nb):
                layout[h, q, max(0, q - w):min(nb, q + w + 1)] = True
            for i, g in enumerate(self.global_block_indices):
                if g >= nb:
                    continue
                g1 = min(self.global_block_end_indices[i],
                         nb) if self.global_block_end_indices else g + 1
                layout[h, :, g:g1] = True  # global columns
                layout[h, g:g1, :] = True  # global rows
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones_like(layout[0]))[None]
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference LocalSlidingWindowSparsityConfig:632)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block, different_layout_per_head=False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for q in range(nb):
            if self.attention == "unidirectional":
                lo = max(0, q - self.num_sliding_window_blocks + 1)
                layout[:, q, lo:q + 1] = True
            else:
                layout[:, q, max(0, q - w):min(nb, q + w + 1)] = True
        return layout
