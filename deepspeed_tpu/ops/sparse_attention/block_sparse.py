"""Block-sparse attention — Pallas TPU kernel + jnp reference.

Reference analog: the Triton block-sparse matmul/softmax kernels
(``deepspeed/ops/sparse_attention/matmul.py:17``, ``softmax.py``) behind
``SparseSelfAttention`` (sparse_self_attention.py:12).  The reference builds
per-layout look-up tables for its Triton kernels; here the same idea drives
a Pallas flash-style kernel using SCALAR PREFETCH: the static LUT of active
key blocks lives in SMEM and feeds the K/V BlockSpec index maps, so the
pipeline stages exactly one [block × Dh] tile of K and V per grid step —
VMEM is O(block·Dh) regardless of sequence length, and compute/HBM traffic
scale with the number of active blocks (O(w·n) for window layouts) instead
of O(n²).

The grid is (batch·heads, query_blocks, lut_width); the online-softmax
running max/sum/accumulator live in VMEM scratch carried across the last
grid dimension (TPU grids execute sequentially, revisiting the same output
block).  Backward reuses the forward LUT for dq and the transposed LUT for
dk/dv.  Layouts are static numpy from ``sparsity_config.py`` — LUTs bake at
trace time, so sparsity never introduces dynamic shapes.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


# ------------------------------------------------------------------ layouts
def layout_to_dense_mask(layout: np.ndarray, block: int) -> np.ndarray:
    """[H, nb, nb] block layout → [H, T, T] boolean element mask."""
    return np.repeat(np.repeat(layout, block, axis=1), block, axis=2)


def _build_luts(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """layout [H, nq, nk] → (lut_q [H, nq, A], lut_k [H, nk, B]) of active
    block indices padded with -1 (A/B = max row/col active count)."""
    h, nq, nk = layout.shape
    a = max(1, int(layout.sum(axis=2).max()))
    b = max(1, int(layout.sum(axis=1).max()))
    lut_q = np.full((h, nq, a), -1, np.int32)
    lut_k = np.full((h, nk, b), -1, np.int32)
    for hi in range(h):
        for q in range(nq):
            idx = np.nonzero(layout[hi, q])[0]
            lut_q[hi, q, :len(idx)] = idx
        for k in range(nk):
            idx = np.nonzero(layout[hi, :, k])[0]
            lut_k[hi, k, :len(idx)] = idx
    return lut_q, lut_k


def _normalize_layout(layout) -> np.ndarray:
    """Dtype-normalize before hashing: raw-byte keys on an int/float layout
    would silently misparse into a garbage LUT."""
    return np.ascontiguousarray(np.asarray(layout) != 0)


def _layout_key(layout: np.ndarray) -> Tuple[bytes, Tuple[int, int, int]]:
    return layout.tobytes(), layout.shape


@functools.lru_cache(maxsize=64)
def _luts_cached(key: bytes, shape: Tuple[int, int, int]):
    layout = np.frombuffer(key, dtype=bool).reshape(shape)
    return _build_luts(layout)


# ------------------------------------------------------------------- kernels
def _masked_p(s, lse_or_mnew):
    """exp(s - ref) with fully-masked entries forced to 0 (an all-masked row
    would otherwise read exp(-inf + inf) = 1 and leak block-0 values)."""
    p = jnp.exp(s - lse_or_mnew)
    return jnp.where(s > _NEG_INF * 0.5, p, 0.0)


def _fwd_kernel(lut_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, block: int, causal: bool,
                scale: float, lut_width: int, num_heads: int):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    j = pl.program_id(2)
    h = jax.lax.rem(bh, num_heads)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    kj = lut_ref[h, qi, j]
    valid = kj >= 0
    q = q_ref[...].astype(jnp.float32) * scale            # [BLK, Dh]
    blk, dh = q.shape
    k = k_ref[...].astype(jnp.float32)                    # [BLK, Dh]
    v = v_ref[...].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [BLK, BLK]
    if causal:
        q_pos = qi * block + jax.lax.broadcasted_iota(jnp.int32, (blk, blk), 0)
        k_pos = jnp.maximum(kj, 0) * block + jax.lax.broadcasted_iota(
            jnp.int32, (blk, blk), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
    s = jnp.where(valid, s, _NEG_INF)

    m_prev, l_prev, acc_prev = m_scr[...], l_scr[...], acc_scr[...]
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    p = _masked_p(s, m_new[:, None])
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + p.sum(axis=-1)
    acc_new = acc_prev * corr[:, None] + jax.lax.dot(p, v)
    m_scr[...], l_scr[...], acc_scr[...] = m_new, l_new, acc_new

    @pl.when(j == lut_width - 1)
    def _finalize():
        l_safe = jnp.maximum(l_new, 1e-20)
        o_ref[...] = jnp.where(l_new[:, None] > 0,
                               acc_new / l_safe[:, None], 0.0).astype(o_ref.dtype)
        # lse carries a trailing unit dim: rank-2 (block, 1) tiles satisfy
        # the TPU block-shape constraint where 1-D tiles do not
        lse_ref[...] = (m_new + jnp.log(l_safe)).astype(jnp.float32)[:, None]


def _bwd_dq_kernel(lut_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, block: int, causal: bool, scale: float,
                   lut_width: int, num_heads: int):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    j = pl.program_id(2)
    h = jax.lax.rem(bh, num_heads)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    kj = lut_ref[h, qi, j]
    valid = kj >= 0
    q = q_ref[...].astype(jnp.float32) * scale
    do = do_ref[...].astype(jnp.float32)
    blk, dh = q.shape
    lse = lse_ref[...][:, 0]
    delta = delta_ref[...][:, 0]
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))
    if causal:
        q_pos = qi * block + jax.lax.broadcasted_iota(jnp.int32, (blk, blk), 0)
        k_pos = jnp.maximum(kj, 0) * block + jax.lax.broadcasted_iota(
            jnp.int32, (blk, blk), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
    s = jnp.where(valid, s, _NEG_INF)
    p = _masked_p(s, lse[:, None])
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
    ds = p * (dp - delta[:, None])
    dq_scr[...] = dq_scr[...] + jax.lax.dot(ds, k)

    @pl.when(j == lut_width - 1)
    def _finalize():
        dq_ref[...] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(lut_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, block: int, causal: bool,
                    scale: float, lut_width: int, num_heads: int):
    bh = pl.program_id(0)
    kj = pl.program_id(1)
    j = pl.program_id(2)
    h = jax.lax.rem(bh, num_heads)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    qi = lut_ref[h, kj, j]
    valid = qi >= 0
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    blk, dh = k.shape
    q = q_ref[...].astype(jnp.float32) * scale
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[...][:, 0]
    delta = delta_ref[...][:, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [BQ, BK]
    if causal:
        q_pos = jnp.maximum(qi, 0) * block + jax.lax.broadcasted_iota(
            jnp.int32, (blk, blk), 0)
        k_pos = kj * block + jax.lax.broadcasted_iota(jnp.int32, (blk, blk), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
    s = jnp.where(valid, s, _NEG_INF)
    p = _masked_p(s, lse[:, None])
    dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())))
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
    ds = p * (dp - delta[:, None])
    dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())))

    @pl.when(j == lut_width - 1)
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


# ----------------------------------------------------------------- host side
def _reshape_bh(x):
    b, t, h, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, dh)


def _unshape_bh(x, b, h):
    bh, t, dh = x.shape
    return x.reshape(b, h, t, dh).transpose(0, 2, 1, 3)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _lut_block_index(lut, num_heads):
    """K/V index map: stage the ACTIVE key block named by the LUT (clamped
    for padding slots, whose contribution the kernel masks out)."""

    def index(bh, qi, j, lut_ref):
        return bh, jnp.maximum(lut_ref[jax.lax.rem(bh, num_heads), qi, j], 0), 0

    return index


def _sparse_attention_fwd(q, k, v, layout, block, causal, scale, interpret):
    b, t, h, dh = q.shape
    nb = t // block
    layout = _normalize_layout(layout)
    assert layout.shape == (h, nb, nb), \
        f"layout {layout.shape} != ({h}, {nb}, {nb})"
    sc = scale if scale is not None else dh ** -0.5
    interp = _interpret_default() if interpret is None else interpret
    lut_q, _ = _luts_cached(*_layout_key(layout))
    a = lut_q.shape[-1]
    qf, kf, vf = _reshape_bh(q), _reshape_bh(k), _reshape_bh(v)
    kernel = functools.partial(_fwd_kernel, block=block, causal=causal,
                               scale=sc, lut_width=a, num_heads=h)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, nb, a),
        in_specs=[
            pl.BlockSpec((None, block, dh), lambda bh, qi, j, lut: (bh, qi, 0)),
            pl.BlockSpec((None, block, dh), _lut_block_index(lut_q, h)),
            pl.BlockSpec((None, block, dh), _lut_block_index(lut_q, h)),
        ],
        out_specs=[
            pl.BlockSpec((None, block, dh), lambda bh, qi, j, lut: (bh, qi, 0)),
            pl.BlockSpec((None, block, 1), lambda bh, qi, j, lut: (bh, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block,), jnp.float32),
            pltpu.VMEM((block,), jnp.float32),
            pltpu.VMEM((block, dh), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, dh), q.dtype),
            jax.ShapeDtypeStruct((b * h, t, 1), jnp.float32),
        ],
        interpret=interp,
    )(jnp.asarray(lut_q), qf, kf, vf)
    return _unshape_bh(out, b, h), (qf, kf, vf, out, lse, (b, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def block_sparse_attention(q, k, v, layout, block: int = 16,
                           causal: bool = False,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """q/k/v: [B, T, H, Dh]; ``layout``: STATIC numpy [H, T//block, T//block]
    bool (hash-keyed for the LUT cache — pass the array from a
    SparsityConfig, not a traced value)."""
    out, _ = _sparse_attention_fwd(q, k, v, layout, block, causal, scale,
                                   interpret)
    return out


def _bsa_fwd_vjp(q, k, v, layout, block, causal, scale, interpret):
    return _sparse_attention_fwd(q, k, v, layout, block, causal, scale,
                                 interpret)


def _bsa_bwd_vjp(layout, block, causal, scale, interpret, res, g):
    qf, kf, vf, outf, lse, (b, h) = res
    bh, t, dh = qf.shape
    nb = t // block
    layout = _normalize_layout(layout)
    sc = scale if scale is not None else dh ** -0.5
    interp = _interpret_default() if interpret is None else interpret
    lut_q, lut_k = _luts_cached(*_layout_key(layout))
    a, bb = lut_q.shape[-1], lut_k.shape[-1]
    dof = _reshape_bh(g)
    delta = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [bh, t, 1]

    qi_block = lambda bh_, qi, j, lut: (bh_, qi, 0)
    dq_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, nb, a),
        in_specs=[
            pl.BlockSpec((None, block, dh), qi_block),
            pl.BlockSpec((None, block, dh), _lut_block_index(lut_q, h)),
            pl.BlockSpec((None, block, dh), _lut_block_index(lut_q, h)),
            pl.BlockSpec((None, block, dh), qi_block),
            pl.BlockSpec((None, block, 1), qi_block),
            pl.BlockSpec((None, block, 1), qi_block),
        ],
        out_specs=pl.BlockSpec((None, block, dh), qi_block),
        scratch_shapes=[pltpu.VMEM((block, dh), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block=block, causal=causal,
                          scale=sc, lut_width=a, num_heads=h),
        grid_spec=dq_grid,
        out_shape=jax.ShapeDtypeStruct((bh, t, dh), qf.dtype),
        interpret=interp,
    )(jnp.asarray(lut_q), qf, kf, vf, dof, lse, delta)

    kv_block = lambda bh_, kj, j, lut: (bh_, kj, 0)
    lut_block = _lut_block_index(lut_k, h)
    dkv_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, nb, bb),
        in_specs=[
            pl.BlockSpec((None, block, dh), lut_block),   # q (active block)
            pl.BlockSpec((None, block, dh), kv_block),    # k (my block)
            pl.BlockSpec((None, block, dh), kv_block),    # v
            pl.BlockSpec((None, block, dh), lut_block),   # do
            pl.BlockSpec((None, block, 1), lut_block),    # lse
            pl.BlockSpec((None, block, 1), lut_block),    # delta
        ],
        out_specs=[
            pl.BlockSpec((None, block, dh), kv_block),
            pl.BlockSpec((None, block, dh), kv_block),
        ],
        scratch_shapes=[pltpu.VMEM((block, dh), jnp.float32),
                        pltpu.VMEM((block, dh), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block=block, causal=causal,
                          scale=sc, lut_width=bb, num_heads=h),
        grid_spec=dkv_grid,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, dh), kf.dtype),
            jax.ShapeDtypeStruct((bh, t, dh), vf.dtype),
        ],
        interpret=interp,
    )(jnp.asarray(lut_k), qf, kf, vf, dof, lse, delta)

    return (_unshape_bh(dq, b, h), _unshape_bh(dk, b, h), _unshape_bh(dv, b, h))


block_sparse_attention.defvjp(_bsa_fwd_vjp, _bsa_bwd_vjp)


# --------------------------------------------------------------- jnp oracle
def block_sparse_attention_reference(q, k, v, layout, block: int = 16,
                                     causal: bool = False,
                                     scale: Optional[float] = None):
    """Dense masked-softmax oracle (numerics ground truth for tests)."""
    b, t, h, dh = q.shape
    sc = scale if scale is not None else dh ** -0.5
    mask = jnp.asarray(layout_to_dense_mask(_normalize_layout(layout), block))
    if causal:
        mask = mask & np.tril(np.ones((t, t), bool))[None]
    qf = q.transpose(0, 2, 1, 3).astype(jnp.float32) * sc     # [B,H,T,Dh]
    kf = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vf = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf)
    s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
    # rows the layout masks entirely produce zeros (kernel semantics), not a
    # uniform average
    any_active = mask.any(axis=-1)                            # [H, T]
    o = jnp.where(any_active[None, :, :, None], o, 0.0)
    return o.transpose(0, 2, 1, 3).astype(q.dtype)
