from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)
from deepspeed_tpu.ops.sparse_attention.block_sparse import (
    block_sparse_attention,
    block_sparse_attention_reference,
    layout_to_dense_mask,
)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention,
)
