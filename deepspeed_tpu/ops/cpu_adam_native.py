"""ctypes surface over the native CPU optimizer kernels
(csrc/adam/dstpu_cpu_adam.cpp; reference ops/adam/cpu_adam.py:13
DeepSpeedCPUAdam binding).

Operates in place on flat fp32 numpy buffers — the host-resident master
params and moments of the ZeRO-Offload path (runtime/zero/offload.py).
"""

from __future__ import annotations

import ctypes

import numpy as np

_LIB = None


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        from deepspeed_tpu.ops import CPUAdamNativeBuilder

        lib = CPUAdamNativeBuilder().load_library()
        lib.dstpu_adam_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_int]
        lib.dstpu_adam_step_fused.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_float,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_int]
        lib.dstpu_adagrad_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_float, ctypes.c_float, ctypes.c_float]
        lib.dstpu_copy_f32_to_bf16.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        _LIB = lib
    return _LIB


def _ptr(a: np.ndarray):
    assert a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.c_void_p)


def available() -> bool:
    from deepspeed_tpu.ops import get_op_builder

    return get_op_builder("cpu_adam_native")().is_compatible()


def adam_step(params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
              exp_avg_sq: np.ndarray, step: int, lr: float,
              betas=(0.9, 0.999), eps: float = 1e-8,
              weight_decay: float = 0.0, adamw_mode: bool = True,
              bias_correction: bool = True) -> None:
    """In-place Adam/AdamW on flat fp32 host buffers. ``step`` is the 1-based
    count including this update."""
    for a in (params, grads, exp_avg, exp_avg_sq):
        assert a.dtype == np.float32 and a.size == params.size
    _lib().dstpu_adam_step(_ptr(params), _ptr(grads), _ptr(exp_avg),
                           _ptr(exp_avg_sq), params.size, step, lr,
                           betas[0], betas[1], eps, weight_decay,
                           int(adamw_mode), int(bias_correction))


def adam_step_fused(params: np.ndarray, grads: np.ndarray,
                    exp_avg: np.ndarray, exp_avg_sq: np.ndarray, step: int,
                    lr: float, betas=(0.9, 0.999), eps: float = 1e-8,
                    weight_decay: float = 0.0, adamw_mode: bool = True,
                    bias_correction: bool = True, grad_scale: float = 1.0,
                    emit_bf16: bool = False):
    """One-pass fused Adam for the offload hot path: grads may be fp32 OR
    bf16 (decoded inline — no separate convert/scale sweeps), ``grad_scale``
    folds the engine's unscale/clip factor in, and with ``emit_bf16`` the
    updated compute-dtype image is written in the same sweep.  Returns the
    bf16 image (ml_dtypes view) or None."""
    import ml_dtypes

    assert params.dtype == np.float32
    for a in (exp_avg, exp_avg_sq):
        assert a.dtype == np.float32 and a.size == params.size
    assert grads.size == params.size
    grads = np.ascontiguousarray(grads)
    if grads.dtype == ml_dtypes.bfloat16:
        g_ptr, g_bf16 = grads.view(np.uint16), 1
    else:
        if grads.dtype != np.float32:  # e.g. fp16 grads from an fp16 engine
            grads = np.ascontiguousarray(grads.astype(np.float32))
        g_ptr, g_bf16 = grads, 0
    out = np.empty(params.shape, np.uint16) if emit_bf16 else None
    _lib().dstpu_adam_step_fused(
        _ptr(params), _ptr(g_ptr), g_bf16, grad_scale, _ptr(exp_avg),
        _ptr(exp_avg_sq), _ptr(out) if out is not None else None,
        params.size, step, lr, betas[0], betas[1], eps, weight_decay,
        int(adamw_mode), int(bias_correction))
    return out.view(ml_dtypes.bfloat16) if out is not None else None


def adagrad_step(params: np.ndarray, grads: np.ndarray, sum_sq: np.ndarray,
                 lr: float, eps: float = 1e-10,
                 weight_decay: float = 0.0) -> None:
    for a in (params, grads, sum_sq):
        assert a.dtype == np.float32 and a.size == params.size
    _lib().dstpu_adagrad_step(_ptr(params), _ptr(grads), _ptr(sum_sq),
                              params.size, lr, eps, weight_decay)


def copy_f32_to_bf16(src: np.ndarray) -> np.ndarray:
    """fp32 → bf16 image (as uint16 bit pattern viewed via ml_dtypes)."""
    assert src.dtype == np.float32
    out = np.empty(src.shape, np.uint16)
    _lib().dstpu_copy_f32_to_bf16(_ptr(np.ascontiguousarray(src)), _ptr(out),
                                  src.size)
    import ml_dtypes

    return out.view(ml_dtypes.bfloat16)
