"""Flash-decode: single-token attention against the KV cache, in Pallas.

Reference counterpart: the fused ``softmax_context`` decode kernel
(csrc/transformer/inference/csrc/softmax.cu + pt_binding.cpp) — one fused
pass over the cache per token instead of materialized score tensors.

Why a kernel when XLA already fuses the einsum path
(ops/attention.decode_attention): two reasons, both measured at
GPT-2-125M batch-8 decode (round 4):

1. **Static-shape cache reads.** The XLA einsum contracts against the
   FULL [B, H, S_max, Dh] cache every step regardless of how many
   positions are valid; with scalar-prefetch the kernel's index_map
   clamps dead key blocks to the last live one (consecutive identical
   fetches are deduped by the pipeline), so HBM traffic tracks the
   VALID prefix (~idx) instead of S_max.
2. **Layout control at batch > 1.** The batched einsum pair
   (QK^T then PV) measured ~2x off the weight+cache streaming roofline
   at B=8; the kernel streams each (batch, kv-head)'s contiguous
   [S, Dh] block once, with the online-softmax state in VMEM.

GQA native: q heads grouped per kv head ([rep, Dh] q tile against the
[S, Dh] cache of their shared kv head). Serving-only: no VJP (training
uses ops/flash_attention.py).

Status per variant (round-4 measurements, PROFILE_DECODE.md):
  * wide-GQA (rep >= 8) MXU-slab kernel — the PRODUCTION route
    (ops/attention.decode_attention gates on rep).
  * MHA head-batched VPU kernel (``_mha_kernel``) — measured SLOWER than
    the XLA einsum it would replace (1.94 vs 1.42 ms/tok at 125M B=8)
    because the decode loop's cache carry is laid out for einsum lane
    parallelism and the pallas operand pays a relayout copy per step.
    Kept test-covered but UNROUTED, pending carry-layout control
    (round 5); delete it instead if that lever never lands.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
DEFAULT_BLOCK_S = 512


def _kernel(idx_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
            block_s: int, ns: int, scale: float):
    sj = pl.program_id(1)

    @pl.when(sj == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    idx = idx_ref[0]
    live = sj * block_s <= idx

    @pl.when(live)
    def _step():
        q = q_ref[...]                                   # [rep, Dh]
        k = k_ref[...]                                   # [BS, Dh]
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [rep, BS] f32
        pos = sj * block_s + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos <= idx, s, _NEG_INF)
        m_prev = m_ref[...][:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = (l_ref[...][:, 0] * corr + p.sum(axis=-1))[:, None]
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new[:, None]

    @pl.when(sj == ns - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[...][:, 0], 1e-20)
        o_ref[...] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def _mha_kernel(idx_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                *, block_s: int, ns: int, scale: float):
    """Head-batched MHA variant: one grid cell per (batch, key-block)
    computes ALL heads' scores with VPU elementwise-multiply + reduce —
    at rep==1 the MXU variant degenerates to [1, Dh] dots and per-cell
    overhead dominates (measured 5x slower than the XLA einsum at 125M
    B=8); here each cell streams the whole [H, BS, Dh] cache block once
    and the math vectorizes over (heads x positions) lanes."""
    sj = pl.program_id(1)

    @pl.when(sj == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    idx = idx_ref[0]
    live = sj * block_s <= idx

    @pl.when(live)
    def _step():
        q = q_ref[...].astype(jnp.float32)               # [H, Dh]
        k = k_ref[...].astype(jnp.float32)               # [H, BS, Dh]
        v = v_ref[...].astype(jnp.float32)
        s = (q[:, None, :] * k).sum(axis=-1) * scale     # [H, BS] on the VPU
        pos = sj * block_s + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos <= idx, s, _NEG_INF)
        m_prev = m_ref[...][:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = (l_ref[...][:, 0] * corr + p.sum(axis=-1))[:, None]
        acc_ref[...] = acc_ref[...] * corr[:, None] + \
            (p[:, :, None] * v).sum(axis=1)              # [H, Dh]
        m_ref[...] = m_new[:, None]

    @pl.when(sj == ns - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[...][:, 0], 1e-20)
        o_ref[...] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def _pick(n: int, pref: int) -> int:
    if n <= pref:
        return n
    while n % pref:
        pref //= 2
    return max(pref, 1)


def flash_decode(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                 cache_index, *, scale: Optional[float] = None,
                 block_s: int = DEFAULT_BLOCK_S,
                 interpret: Optional[bool] = None) -> jax.Array:
    """``q [B, 1, Hq, Dh]`` against head-major ``[B, Hkv, S, Dh]`` caches
    whose position ``cache_index`` holds q's own K/V (already written).
    Returns ``[B, 1, Hq, Dh]``."""
    b, t, hq, dh = q.shape
    assert t == 1, "flash_decode is the single-token path"
    hkv, s_max = k_cache.shape[1], k_cache.shape[2]
    rep = hq // hkv
    sc = scale if scale is not None else dh ** -0.5
    bs = _pick(s_max, block_s)
    ns = s_max // bs
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    idx = jnp.asarray(cache_index, jnp.int32).reshape(1)

    if rep == 1:
        # MHA: head-batched VPU kernel — grid over (batch, key blocks)
        qf = q.reshape(b, hq, dh)
        kernel = functools.partial(_mha_kernel, block_s=bs, ns=ns, scale=sc)

        def live_block4(bi, sj, idx_ref):
            return (bi, 0, jnp.minimum(sj, idx_ref[0] // bs), 0)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, ns),
            in_specs=[
                pl.BlockSpec((None, hq, dh),
                             lambda bi, sj, idx_ref: (bi, 0, 0)),
                pl.BlockSpec((None, hkv, bs, dh), live_block4),
                pl.BlockSpec((None, hkv, bs, dh), live_block4),
            ],
            out_specs=pl.BlockSpec((None, hq, dh),
                                   lambda bi, sj, idx_ref: (bi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((hq, 1), jnp.float32),   # running max
                pltpu.VMEM((hq, 1), jnp.float32),   # running sum
                pltpu.VMEM((hq, dh), jnp.float32),  # output accumulator
            ],
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, hq, dh), q.dtype),
            interpret=interpret,
        )(idx, qf, k_cache, v_cache)
        return out[:, None]

    # GQA: [B, 1, Hq, Dh] -> [B*Hkv, rep, Dh]; the [rep, Dh] q tile feeds
    # the MXU a real slab per kv head
    qf = q.reshape(b, hkv, rep, dh).reshape(b * hkv, rep, dh)
    kf = k_cache.reshape(b * hkv, s_max, dh)
    vf = v_cache.reshape(b * hkv, s_max, dh)
    kernel = functools.partial(_kernel, block_s=bs, ns=ns, scale=sc)

    def live_block(bh, sj, idx_ref):
        # clamp dead key blocks onto the last live one: the pipeline dedups
        # consecutive identical fetches, so HBM traffic follows the valid
        # prefix, not S_max
        return (bh, jnp.minimum(sj, idx_ref[0] // bs), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, ns),
        in_specs=[
            pl.BlockSpec((None, rep, dh), lambda bh, sj, idx_ref: (bh, 0, 0)),
            pl.BlockSpec((None, bs, dh), live_block),
            pl.BlockSpec((None, bs, dh), live_block),
        ],
        out_specs=pl.BlockSpec((None, rep, dh),
                               lambda bh, sj, idx_ref: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),   # running max
            pltpu.VMEM((rep, 1), jnp.float32),   # running sum
            pltpu.VMEM((rep, dh), jnp.float32),  # output accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * hkv, rep, dh), q.dtype),
        interpret=interpret,
    )(idx, qf, kf, vf)
    return out.reshape(b, hkv * rep, dh)[:, None]
