from deepspeed_tpu.ops.native.builder import NativeOpBuilder, build_native_lib
