"""JIT builder for native C++ host-side ops.

The reference compiles CUDA/C++ torch extensions at first ``load()``
(op_builder/builder.py:434-497: hash sources, compile into a per-user build
dir, dlopen).  Here the native ops are plain C shared libraries bound via
ctypes — no torch, no pybind11 — because they operate on raw host memory
(numpy buffers) handed over by the JAX host runtime:

  sources → g++ -O3 -fPIC -shared (-fopenmp, -mavx2 when supported)
          → ~/.cache/dstpu_ops/<name>-<hash>.so → ctypes.CDLL

Compatibility detection mirrors ``OpBuilder.is_compatible``: a missing
toolchain or failed SIMD probe downgrades flags rather than failing, and
callers can interrogate availability via the op registry.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
from pathlib import Path
from typing import List, Optional

from deepspeed_tpu.ops.registry import OpBuilder
from deepspeed_tpu.utils.logging import logger

_REPO_CSRC = Path(__file__).resolve().parents[3] / "csrc"


def _build_dir() -> Path:
    d = os.environ.get("DSTPU_BUILD_DIR")
    if d:
        p = Path(d)
    else:
        p = Path(os.path.expanduser("~/.cache/dstpu_ops"))
    p.mkdir(parents=True, exist_ok=True)
    return p


def _compiler() -> Optional[str]:
    for cc in ("g++", "c++", "clang++"):
        if shutil.which(cc):
            return cc
    return None


def _probe_flag(cc: str, flag: str) -> bool:
    """Does the toolchain accept ``flag``? (cpu-arch detection analog of
    reference builder.py:318 SIMD width probing)."""
    with tempfile.TemporaryDirectory() as td:
        src = Path(td) / "probe.cpp"
        src.write_text("int main(){return 0;}\n")
        try:
            r = subprocess.run([cc, flag, str(src), "-o", str(Path(td) / "a.out")],
                               capture_output=True, timeout=60)
            return r.returncode == 0
        except Exception:
            return False


_FLAG_CACHE: dict = {}


def _supported(cc: str, flag: str) -> bool:
    key = (cc, flag)
    if key not in _FLAG_CACHE:
        _FLAG_CACHE[key] = _probe_flag(cc, flag)
    return _FLAG_CACHE[key]


def build_native_lib(name: str, sources: List[str], extra_flags: List[str] = (),
                     want_openmp: bool = False, want_simd: bool = False) -> Path:
    """Compile ``sources`` (paths relative to csrc/) into a cached .so."""
    cc = _compiler()
    if cc is None:
        raise RuntimeError("no C++ compiler found (g++/clang++)")
    srcs = [str(_REPO_CSRC / s) for s in sources]
    flags = [cc, "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread"]
    if want_openmp and _supported(cc, "-fopenmp"):
        flags.append("-fopenmp")
    if want_simd:
        for simd in ("-mavx512f", "-mavx2"):
            if _supported(cc, simd):
                flags.append(simd)
                break
    flags += list(extra_flags)
    h = hashlib.sha256()
    for s in srcs:
        h.update(Path(s).read_bytes())
    h.update(" ".join(flags).encode())  # compiler + resolved flags key the cache
    out = _build_dir() / f"{name}-{h.hexdigest()[:16]}.so"
    if out.exists():
        return out
    tmp = f"{out}.{os.getpid()}.tmp"  # unique per process: concurrent ranks race
    cmd = flags + srcs + ["-o", tmp]
    logger.info(f"building native op '{name}': {' '.join(cmd)}")
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        raise RuntimeError(f"native build of '{name}' failed:\n{r.stderr}")
    os.replace(tmp, out)
    return out


class NativeOpBuilder(OpBuilder):
    """Base for ops backed by a C++ shared library (AIO, CPU optimizers)."""

    SOURCES: List[str] = []
    WANT_OPENMP = False
    WANT_SIMD = False

    def is_compatible(self, verbose: bool = False) -> bool:
        if _compiler() is None:
            if verbose:
                logger.warning(f"{self.NAME}: no C++ compiler on PATH")
            return False
        return all((_REPO_CSRC / s).exists() for s in self.SOURCES)

    def compatibility_reason(self) -> str:
        if _compiler() is None:
            return "no C++ compiler found"
        missing = [s for s in self.SOURCES if not (_REPO_CSRC / s).exists()]
        return f"missing sources: {missing}" if missing else "compatible"

    def load_library(self) -> ctypes.CDLL:
        path = build_native_lib(self.NAME, self.SOURCES,
                                want_openmp=self.WANT_OPENMP,
                                want_simd=self.WANT_SIMD)
        return ctypes.CDLL(str(path))
