"""Pallas flash attention (TPU kernel) — FlashAttention-2 style.

Reference counterpart: the fused attention CUDA kernels
(``csrc/transformer/softmax_kernels.cu`` training softmax,
``csrc/transformer/inference/csrc/softmax.cu``) — on TPU the fused,
memory-efficient form is a Pallas kernel tiled for the MXU: O(block) VMEM
per grid step instead of materializing the [T, T] score matrix in HBM.

Layout: inputs [B, T, H, Dh] (framework-standard). The key/value walk is a
GRID dimension (not an in-kernel loop over a VMEM-resident K/V copy), so
VMEM holds only (block_q x Dh) + (block_k x Dh) tiles at any sequence
length — double-buffered full-T K/V residency OOM'd scoped VMEM at
seq 8192. Online-softmax state (m, l, acc) lives in VMEM scratch carried
across the innermost (sequential) grid dimension; causal skipping masks
whole blocks above the diagonal via ``pl.when``. The backward pass is the
standard two-kernel FA2 recomputation (dq; dk/dv) using the saved
log-sum-exp rows, with the same grid structure. Matmuls run in the storage
dtype (bf16 on the training path — full MXU rate) with f32 accumulation.
Precision note: the P·V, dS·K, P^T·dO and dS^T·Q products therefore see
their p/ds operand ROUNDED to the storage dtype before the MXU — the
standard FA2-on-bf16 tradeoff, but a change vs all-f32 operands; set
``DSTPU_FLASH_F32_PRECISE=1`` to keep those operands in f32 (half MXU
rate) for tolerance-sensitive runs.
Known tradeoff: causally-masked grid steps skip COMPUTE via ``pl.when`` but
still fetch their K/V tiles (Pallas grids are rectangular) — ~2x the K/V
bandwidth of a bounded walk on the causal path; measured wins at seq
1024-8192 absorb it (tiles are small vs the T^2 compute), revisit with a
per-qi bounded inner loop if a profile ever shows fetch-bound behavior.
Composes with ring attention (ops/ring_attention.py) for sequence lengths
beyond one chip.

Exposed as ``flash_attention(q, k, v, causal=...)`` with a custom_vjp;
``interpret=True`` (CPU tests) runs the same kernels in the Pallas
interpreter, so TPU and test paths share every line of kernel code.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 512-blocks amortize per-grid-step overhead (measured 2026-07-31 on-chip:
# (512,512) >> (256,256) > (128,128) for fwd+bwd at seq 2048; (1024,1024)
# regresses — the [bq,bk] f32 score tile outgrows VMEM headroom)
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _dot_f32(a, b, dims):
    """MXU-native matmul: inputs stay in their storage dtype (bf16 on the
    training path — full MXU rate), accumulation in f32."""
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _mm_dtype(storage_dtype):
    """Dtype for the computed p/ds operands of the second-stage matmuls:
    the storage dtype (full MXU rate) unless DSTPU_FLASH_F32_PRECISE=1
    opts back into all-f32 operands (see module docstring)."""
    import os

    if os.environ.get("DSTPU_FLASH_F32_PRECISE") == "1":
        return jnp.float32
    return storage_dtype


def _causal_mask(s, qi, kj, block_q, block_k):
    bq, bk = s.shape
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(k_pos <= q_pos, s, _NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *,
                causal: bool, scale: float, block_q: int, block_k: int,
                nk: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: key block strictly above the diagonal contributes nothing
    live = (kj * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[...]                                  # [BQ, Dh]
        k = k_ref[...]                                  # [BK, Dh]
        v = v_ref[...]
        s = _dot_f32(q, k, ((1,), (1,))) * scale        # [BQ, BK] f32
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k)
        m_prev = m_ref[...][:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = (l_ref[...][:, 0] * corr + p.sum(axis=-1))[:, None]
        acc_ref[...] = acc_ref[...] * corr[:, None] + \
            _dot_f32(p.astype(_mm_dtype(v.dtype)), v, ((1,), (0,)))
        m_ref[...] = m_new[:, None]

    @pl.when(kj == nk - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[...][:, 0], 1e-20)
        o_ref[...] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        # trailing unit dim: rank-2 (bq, 1) tiles satisfy the TPU block-shape
        # constraint (1-D tiles fail Mosaic lowering)
        lse_ref[...] = (m_ref[...][:, 0] + jnp.log(l_safe))[:, None]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc_ref, *, causal: bool, scale: float, block_q: int,
                   block_k: int, nk: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    live = (kj * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][:, 0]
        delta = delta_ref[...][:, 0]
        s = _dot_f32(q, k, ((1,), (1,))) * scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = _dot_f32(do, v, ((1,), (1,)))
        ds = p * (dp - delta[:, None])
        dq_acc_ref[...] += _dot_f32(ds.astype(_mm_dtype(k.dtype)), k, ((1,), (0,)))

    @pl.when(kj == nk - 1)
    def _finish():
        dq_ref[...] = (dq_acc_ref[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, causal: bool,
                    scale: float, block_q: int, block_k: int, nq: int):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # causal: query block strictly before this key block sees none of it
    live = (qi * block_q + block_q - 1 >= kj * block_k) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][:, 0]
        delta = delta_ref[...][:, 0]
        s = _dot_f32(q, k, ((1,), (1,))) * scale        # [BQ, BK]
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dv_acc_ref[...] += _dot_f32(p.astype(_mm_dtype(do.dtype)), do, ((0,), (0,)))
        dp = _dot_f32(do, v, ((1,), (1,)))
        ds = p * (dp - delta[:, None])
        dk_acc_ref[...] += _dot_f32(ds.astype(_mm_dtype(q.dtype)), q, ((0,), (0,)))

    @pl.when(qi == nq - 1)
    def _finish():
        # s was computed from UNSCALED q, so dk carries the softmax scale
        dk_ref[...] = (dk_acc_ref[...] * scale).astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _reshape_bh(x):
    b, t, h, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, dh)


def _unshape_bh(x, b, h):
    bh, t, dh = x.shape
    return x.reshape(b, h, t, dh).transpose(0, 2, 1, 3)


def _pick_block(t: int, pref: int) -> int:
    blk = min(pref, t)
    while t % blk:
        blk //= 2
    return max(blk, 1)


@functools.lru_cache(maxsize=1)
def vma_typing_supported() -> bool:
    """True when this JAX carries shard_map varying-axis (vma) typing
    (aval ``.vma`` + ``ShapeDtypeStruct(vma=...)``). On versions predating
    it, ``_sds``'s getattr silently finds no vma, so strict-checked
    shard_map would reject pallas_call outputs opaquely — callers
    (ops/ring_attention.py) use this to fall back to check_vma=False."""
    try:
        jax.ShapeDtypeStruct((1,), jnp.float32, vma=frozenset())
        return hasattr(jax.typeof(jnp.zeros(())), "vma")
    except Exception:
        # any probe failure (TypeError on old ShapeDtypeStruct, AttributeError
        # when jax.typeof is absent, ...) degrades to check_vma=False
        return False


def _sds(*operands_then_args):
    """ShapeDtypeStruct factory that propagates shard_map varying-axes (vma)
    typing from the kernel operands — pallas_call under `shard_map` with
    check_vma requires outputs to declare how they vary over mesh axes
    (e.g. the Ulysses head-scatter path)."""
    *operands, shape, dtype = operands_then_args
    vma = frozenset()
    typeof = getattr(jax, "typeof", None)  # absent on older jax: no vma
    for op in (operands if typeof is not None else ()):
        vma |= frozenset(getattr(typeof(op), "vma", ()) or ())
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _grid_params(seq_semantics=("parallel", "parallel", "arbitrary")):
    try:
        return pltpu.CompilerParams(dimension_semantics=seq_semantics)
    except Exception:  # older naming
        return pltpu.TPUCompilerParams(dimension_semantics=seq_semantics)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """q/k/v: [B, T, H, Dh] → [B, T, H, Dh]. MHA (same head counts)."""
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def flash_fwd_parts(qf, kf, vf, *, causal, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Kernel-level forward on FLAT [BH, T, Dh] operands → (out, lse).

    Public building block for sequence-parallel composition (ring attention
    merges per-hop (out, lse) pairs exactly); ``flash_attention`` wraps it
    with the [B, T, H, Dh] layout and custom_vjp."""
    bh, t, dh = qf.shape
    sc = scale if scale is not None else dh ** -0.5
    bq = _pick_block(t, block_q)
    bk = _pick_block(kf.shape[1], block_k)
    nq, nk = t // bq, kf.shape[1] // bk
    interp = _interpret_default() if interpret is None else interpret
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=sc,
                               block_q=bq, block_k=bk, nk=nk)
    kw = {} if interp else {"compiler_params": _grid_params()}
    shp = functools.partial(_sds, qf, kf, vf)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((None, bq, dh), lambda bh_, qi, kj: (bh_, qi, 0)),
            pl.BlockSpec((None, bk, dh), lambda bh_, qi, kj: (bh_, kj, 0)),
            pl.BlockSpec((None, bk, dh), lambda bh_, qi, kj: (bh_, kj, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, dh), lambda bh_, qi, kj: (bh_, qi, 0)),
            pl.BlockSpec((None, bq, 1), lambda bh_, qi, kj: (bh_, qi, 0)),
        ],
        out_shape=[
            shp((bh, t, dh), qf.dtype),
            shp((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),    # running max m
            pltpu.VMEM((bq, 1), jnp.float32),    # running sum l
            pltpu.VMEM((bq, dh), jnp.float32),   # output accumulator
        ],
        interpret=interp,
        **kw,
    )(qf, kf, vf)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    b, t, h, dh = q.shape
    qf, kf, vf = _reshape_bh(q), _reshape_bh(k), _reshape_bh(v)
    out, lse = flash_fwd_parts(qf, kf, vf, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)
    # Residuals tagged for remat: the "flash_res" checkpoint-name lets the
    # save_attn policy (runtime/activation_checkpointing.py) SAVE them, so a
    # rematted transformer block never re-runs this kernel in backward —
    # flash residuals are O(T) (out + lse), unlike dense attention's O(T^2).
    from jax.ad_checkpoint import checkpoint_name

    res = tuple(checkpoint_name(x, "flash_res") for x in (qf, kf, vf, out, lse))
    return _unshape_bh(out, b, h), res + ((b, h),)


def _flash_fwd_vjp(q, k, v, causal, scale, block_q, block_k, interpret):
    out, res = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, res


def flash_bwd_parts(qf, kf, vf, dof, lse, delta, *, causal, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Kernel-level backward on FLAT operands → (dq, dk, dv).

    ``lse``/``delta`` are the GLOBAL log-sum-exp rows / do·out sums, so
    sequence-parallel callers can run this per K/V hop and the per-hop
    grads sum to the exact global gradient (p = exp(s - lse_global))."""
    bh, t, dh = qf.shape
    sc = scale if scale is not None else dh ** -0.5
    bq = _pick_block(t, block_q)
    bk = _pick_block(kf.shape[1], block_k)
    nq, nk = t // bq, kf.shape[1] // bk
    interp = _interpret_default() if interpret is None else interpret
    kw = {} if interp else {"compiler_params": _grid_params()}
    shp = functools.partial(_sds, qf, kf, vf, dof)

    dq_kernel = functools.partial(_bwd_dq_kernel, causal=causal, scale=sc,
                                  block_q=bq, block_k=bk, nk=nk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((None, bq, dh), lambda b_, qi, kj: (b_, qi, 0)),
            pl.BlockSpec((None, bk, dh), lambda b_, qi, kj: (b_, kj, 0)),
            pl.BlockSpec((None, bk, dh), lambda b_, qi, kj: (b_, kj, 0)),
            pl.BlockSpec((None, bq, dh), lambda b_, qi, kj: (b_, qi, 0)),
            pl.BlockSpec((None, bq, 1), lambda b_, qi, kj: (b_, qi, 0)),
            pl.BlockSpec((None, bq, 1), lambda b_, qi, kj: (b_, qi, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, dh), lambda b_, qi, kj: (b_, qi, 0)),
        out_shape=shp((bh, t, dh), qf.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32)],
        interpret=interp,
        **kw,
    )(qf, kf, vf, dof, lse, delta)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, causal=causal, scale=sc,
                                   block_q=bq, block_k=bk, nq=nq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((None, bq, dh), lambda b_, kj, qi: (b_, qi, 0)),
            pl.BlockSpec((None, bk, dh), lambda b_, kj, qi: (b_, kj, 0)),
            pl.BlockSpec((None, bk, dh), lambda b_, kj, qi: (b_, kj, 0)),
            pl.BlockSpec((None, bq, dh), lambda b_, kj, qi: (b_, qi, 0)),
            pl.BlockSpec((None, bq, 1), lambda b_, kj, qi: (b_, qi, 0)),
            pl.BlockSpec((None, bq, 1), lambda b_, kj, qi: (b_, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, dh), lambda b_, kj, qi: (b_, kj, 0)),
            pl.BlockSpec((None, bk, dh), lambda b_, kj, qi: (b_, kj, 0)),
        ],
        out_shape=[
            shp((kf.shape[0], kf.shape[1], dh), kf.dtype),
            shp((kf.shape[0], kf.shape[1], dh), vf.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, dh), jnp.float32),
            pltpu.VMEM((bk, dh), jnp.float32),
        ],
        interpret=interp,
        **kw,
    )(qf, kf, vf, dof, lse, delta)
    return dq, dk, dv


def _flash_bwd_vjp(causal, scale, block_q, block_k, interpret, res, g):
    qf, kf, vf, outf, lse, (b, h) = res
    dof = _reshape_bh(g)
    delta = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [bh, t, 1]
    dq, dk, dv = flash_bwd_parts(qf, kf, vf, dof, lse, delta, causal=causal,
                                 scale=scale, block_q=block_q,
                                 block_k=block_k, interpret=interpret)
    return (_unshape_bh(dq, b, h), _unshape_bh(dk, b, h), _unshape_bh(dv, b, h))


flash_attention.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)
