"""Pallas flash attention (TPU kernel) — FlashAttention-2 style.

Reference counterpart: the fused attention CUDA kernels
(``csrc/transformer/softmax_kernels.cu`` training softmax,
``csrc/transformer/inference/csrc/softmax.cu``) — on TPU the fused,
memory-efficient form is a Pallas kernel tiled for the MXU: O(T) VMEM per
query block instead of materializing the [T, T] score matrix in HBM.

Layout: inputs [B, T, H, Dh] (framework-standard); kernels run per (b·h)
with a grid over query blocks; K/V for the (b·h) live in VMEM and are
scanned block-by-block with an online softmax. The backward pass is the
standard two-kernel FA2 recomputation (dq; dk/dv) using the saved
log-sum-exp rows. Composes with ring attention (ops/ring_attention.py) for
sequence lengths beyond one chip's VMEM.

Exposed as ``flash_attention(q, k, v, causal=...)`` with a custom_vjp;
``interpret=True`` (CPU tests) runs the same kernels in the Pallas
interpreter, so TPU and test paths share every line of kernel code.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _dot_f32(a, b, dims):
    """MXU-native matmul: inputs stay in their storage dtype (bf16 on the
    training path — full MXU rate), accumulation in f32."""
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool, scale: float, seq_len: int, block_q: int):
    qi = pl.program_id(1)
    q = q_ref[...]                                      # [BQ, Dh] storage dtype
    bq, dh = q.shape
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    nk = seq_len // block_k

    def body(kj, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(kj * block_k, block_k), :]      # [BK, Dh]
        v = v_ref[pl.ds(kj * block_k, block_k), :]
        s = _dot_f32(q, k, ((1,), (1,))) * scale        # [BQ, BK] f32
        if causal:
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[:, None] + _dot_f32(p.astype(v.dtype), v, ((1,), (0,)))
        return m_new, l, acc

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, dh), jnp.float32)
    if causal:
        # skip key blocks strictly after this query block
        nk_eff = jnp.minimum(nk, (qi * block_q + block_q + block_k - 1) // block_k)
    else:
        nk_eff = nk
    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-20)
    o_ref[...] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # trailing unit dim: rank-2 (bq, 1) tiles satisfy the TPU block-shape
    # constraint (1-D tiles fail Mosaic lowering)
    lse_ref[...] = (m + jnp.log(l_safe)).astype(jnp.float32)[:, None]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   block_k: int, causal: bool, scale: float, seq_len: int,
                   block_q: int):
    qi = pl.program_id(1)
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...][:, 0]
    delta = delta_ref[...][:, 0]
    bq, dh = q.shape
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    nk = seq_len // block_k

    def body(kj, dq):
        k = k_ref[pl.ds(kj * block_k, block_k), :]
        v = v_ref[pl.ds(kj * block_k, block_k), :]
        s = _dot_f32(q, k, ((1,), (1,))) * scale
        if causal:
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = _dot_f32(do, v, ((1,), (1,)))
        ds = p * (dp - delta[:, None])
        return dq + _dot_f32(ds.astype(k.dtype), k, ((1,), (0,)))

    if causal:
        nk_eff = jnp.minimum(nk, (qi * block_q + block_q + block_k - 1) // block_k)
    else:
        nk_eff = nk
    dq = jax.lax.fori_loop(0, nk_eff, body, jnp.zeros((bq, dh), jnp.float32))
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q: int, causal: bool, scale: float,
                    seq_len: int, block_k: int):
    kj = pl.program_id(1)
    k = k_ref[...]
    v = v_ref[...]
    bk, dh = k.shape
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
    nq = seq_len // block_q

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qi * block_q, block_q), :]
        do = do_ref[pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[pl.ds(qi * block_q, block_q), 0]
        s = _dot_f32(q, k, ((1,), (1,))) * scale  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        pb = p.astype(do.dtype)
        dv = dv + _dot_f32(pb, do, ((0,), (0,)))
        dp = _dot_f32(do, v, ((1,), (1,)))
        ds = p * (dp - delta[:, None])
        dk = dk + _dot_f32(ds.astype(q.dtype), q, ((0,), (0,)))
        return dk, dv

    if causal:
        q_start = (kj * block_k) // block_q  # first query block that sees us
    else:
        q_start = 0
    dk0 = jnp.zeros((bk, dh), jnp.float32)
    dv0 = jnp.zeros((bk, dh), jnp.float32)
    dk, dv = jax.lax.fori_loop(q_start, nq, body, (dk0, dv0))
    # s was computed from UNSCALED q, so dk needs the softmax scale (like dq)
    dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _reshape_bh(x):
    b, t, h, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, dh)


def _unshape_bh(x, b, h):
    bh, t, dh = x.shape
    return x.reshape(b, h, t, dh).transpose(0, 2, 1, 3)


def _pick_block(t: int, pref: int) -> int:
    blk = min(pref, t)
    while t % blk:
        blk //= 2
    return max(blk, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """q/k/v: [B, T, H, Dh] → [B, T, H, Dh]. MHA (same head counts)."""
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    b, t, h, dh = q.shape
    sc = scale if scale is not None else dh ** -0.5
    bq = _pick_block(t, block_q)
    bk = _pick_block(t, block_k)
    interp = _interpret_default() if interpret is None else interpret
    qf, kf, vf = _reshape_bh(q), _reshape_bh(k), _reshape_bh(v)
    grid = (b * h, t // bq)
    kernel = functools.partial(_fwd_kernel, block_k=bk, causal=causal,
                               scale=sc, seq_len=t, block_q=bq)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, bq, dh), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, t, dh), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, t, dh), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, dh), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, bq, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, dh), q.dtype),
            jax.ShapeDtypeStruct((b * h, t, 1), jnp.float32),
        ],
        interpret=interp,
    )(qf, kf, vf)
    # Residuals tagged for remat: the "flash_res" checkpoint-name lets the
    # save_attn policy (runtime/activation_checkpointing.py) SAVE them, so a
    # rematted transformer block never re-runs this kernel in backward —
    # flash residuals are O(T) (out + lse), unlike dense attention's O(T^2).
    from jax.ad_checkpoint import checkpoint_name

    res = tuple(checkpoint_name(x, "flash_res") for x in (qf, kf, vf, out, lse))
    return _unshape_bh(out, b, h), res + ((b, h),)


def _flash_fwd_vjp(q, k, v, causal, scale, block_q, block_k, interpret):
    out, res = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, res


def _flash_bwd_vjp(causal, scale, block_q, block_k, interpret, res, g):
    qf, kf, vf, outf, lse, (b, h) = res
    bh, t, dh = qf.shape
    sc = scale if scale is not None else dh ** -0.5
    bq = _pick_block(t, block_q)
    bk = _pick_block(t, block_k)
    interp = _interpret_default() if interpret is None else interpret
    dof = _reshape_bh(g)
    delta = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [bh, t, 1]

    dq_kernel = functools.partial(_bwd_dq_kernel, block_k=bk, causal=causal,
                                  scale=sc, seq_len=t, block_q=bq)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, t // bq),
        in_specs=[
            pl.BlockSpec((None, bq, dh), lambda b_, qi: (b_, qi, 0)),
            pl.BlockSpec((None, t, dh), lambda b_, qi: (b_, 0, 0)),
            pl.BlockSpec((None, t, dh), lambda b_, qi: (b_, 0, 0)),
            pl.BlockSpec((None, bq, dh), lambda b_, qi: (b_, qi, 0)),
            pl.BlockSpec((None, bq, 1), lambda b_, qi: (b_, qi, 0)),
            pl.BlockSpec((None, bq, 1), lambda b_, qi: (b_, qi, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, dh), lambda b_, qi: (b_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, dh), qf.dtype),
        interpret=interp,
    )(qf, kf, vf, dof, lse, delta)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, block_q=bq, causal=causal,
                                   scale=sc, seq_len=t, block_k=bk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, t // bk),
        in_specs=[
            pl.BlockSpec((None, t, dh), lambda b_, kj: (b_, 0, 0)),
            pl.BlockSpec((None, bk, dh), lambda b_, kj: (b_, kj, 0)),
            pl.BlockSpec((None, bk, dh), lambda b_, kj: (b_, kj, 0)),
            pl.BlockSpec((None, t, dh), lambda b_, kj: (b_, 0, 0)),
            pl.BlockSpec((None, t, 1), lambda b_, kj: (b_, 0, 0)),
            pl.BlockSpec((None, t, 1), lambda b_, kj: (b_, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, dh), lambda b_, kj: (b_, kj, 0)),
            pl.BlockSpec((None, bk, dh), lambda b_, kj: (b_, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, dh), kf.dtype),
            jax.ShapeDtypeStruct((bh, t, dh), vf.dtype),
        ],
        interpret=interp,
    )(qf, kf, vf, dof, lse, delta)

    return (_unshape_bh(dq, b, h), _unshape_bh(dk, b, h), _unshape_bh(dv, b, h))


flash_attention.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)
