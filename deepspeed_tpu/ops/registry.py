"""Op registry — TPU-native analog of the reference's ``op_builder`` system
(op_builder/builder.py: OpBuilder.load / is_compatible, op_builder/all_ops.py
ALL_OPS registry).

The reference JIT-compiles CUDA extensions; here an "op" is a JAX callable
with (possibly) a Pallas fast path and a pure-jnp reference fallback. The
builder seam is kept: name → builder → ``is_compatible()`` → ``load()``,
so callers (and ``ds_report``) can interrogate availability exactly like the
reference, and future Mosaic/C++ host ops slot in behind the same interface.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Type


class OpBuilder:
    NAME: str = "abstract"

    def __init__(self, accelerator=None):
        from deepspeed_tpu.accelerator import get_accelerator

        self.accelerator = accelerator or get_accelerator()

    def is_compatible(self, verbose: bool = False) -> bool:
        return True

    def compatibility_reason(self) -> str:
        return "compatible"

    def load(self):
        """Return the op implementation (module or callable)."""
        raise NotImplementedError

    @property
    def name(self) -> str:
        return self.NAME


class PallasOpBuilder(OpBuilder):
    """Ops whose fast path is a Pallas TPU kernel; falls back to jnp on
    non-TPU backends (interpret mode is used only in tests)."""

    def is_compatible(self, verbose: bool = False) -> bool:
        return True  # jnp fallback always exists

    def has_fast_path(self) -> bool:
        return self.accelerator.name() == "tpu"


_OP_BUILDERS: Dict[str, Type[OpBuilder]] = {}


def register_op_builder(cls: Type[OpBuilder]) -> Type[OpBuilder]:
    _OP_BUILDERS[cls.NAME] = cls
    return cls


def get_op_builder(name: str) -> Type[OpBuilder]:
    from . import _register_all  # noqa: F401  (populate registry lazily)

    if name not in _OP_BUILDERS:
        raise KeyError(f"unknown op builder '{name}'. known: {sorted(_OP_BUILDERS)}")
    return _OP_BUILDERS[name]


def all_ops() -> Dict[str, Type[OpBuilder]]:
    from . import _register_all  # noqa: F401

    return dict(_OP_BUILDERS)
