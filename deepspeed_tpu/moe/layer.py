"""MoE layer — analog of reference ``deepspeed/moe/layer.py`` (MoE:16) and
``MOELayer.forward`` (sharded_moe.py:473).

The reference pipeline per layer: gate → einsum dispatch → all_to_all →
local experts → all_to_all → combine. Here the same einsums carry sharding
constraints instead of manual collectives: tokens are sharded over the batch
axes, the dispatched [E, C, M] tensor is constrained to shard E over the
'expert' mesh axis, and XLA inserts the ICI all-to-alls (both directions)
with overlap — SURVEY §2.2 row EP.

Expert parameters carry a leading expert dim sharded over 'expert' (logical
axis name "expert" → EXPERT_AXIS in the partition plan), which also gives the
expert-data-parallel gradient averaging over the remaining 'data' axis for
free (reference needs dedicated expert-data-parallel groups,
utils/groups.py:202).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.moe.sharded_moe import TopKGate
from deepspeed_tpu.parallel.topology import BATCH_AXES, EXPERT_AXIS


def _constrain(x, *spec):
    """Apply a sharding constraint when running under a mesh; no-op otherwise."""
    try:
        return jax.lax.with_sharding_constraint(x, P(*spec))
    except (ValueError, RuntimeError):
        return x


@dataclasses.dataclass
class ExpertFFN:
    """The local expert stack: [E_local experts each a 2-layer FFN]."""

    model_dim: int
    ffn_dim: int
    num_experts: int

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        scale_in = self.model_dim ** -0.5
        scale_out = self.ffn_dim ** -0.5
        return {
            "w1": jax.random.normal(k1, (self.num_experts, self.model_dim, self.ffn_dim),
                                    jnp.float32) * scale_in,
            "b1": jnp.zeros((self.num_experts, self.ffn_dim)),
            "w2": jax.random.normal(k2, (self.num_experts, self.ffn_dim, self.model_dim),
                                    jnp.float32) * scale_out,
            "b2": jnp.zeros((self.num_experts, self.model_dim)),
        }

    @staticmethod
    def logical_axes():
        return {"w1": ("expert", "hidden", "mlp"), "b1": ("expert", "mlp"),
                "w2": ("expert", "mlp", "hidden"), "b2": ("expert", "hidden")}

    def apply(self, params, x):
        """x: [E, C, M] dispatched tokens; per-expert FFN via batched einsum."""
        h = jnp.einsum("ecm,emf->ecf", x, params["w1"].astype(x.dtype))
        h = h + params["b1"].astype(x.dtype)[:, None, :]
        h = jax.nn.gelu(h, approximate=True)
        out = jnp.einsum("ecf,efm->ecm", h, params["w2"].astype(x.dtype))
        return out + params["b2"].astype(x.dtype)[:, None, :]


class MoE:
    """Drop-in FFN replacement (reference MoE layer.py:16).

    apply(params, x, train, rng) -> (out, l_aux, exp_counts); x: [B, T, M].
    """

    def __init__(self, hidden_size: int, num_experts: int, ffn_dim: Optional[int] = None,
                 k: int = 1, capacity_factor: float = 1.25,
                 eval_capacity_factor: float = 2.0, min_capacity: int = 4,
                 noisy_gate_policy: Optional[str] = None, drop_tokens: bool = True,
                 use_residual: bool = False):
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ffn_dim = ffn_dim or 4 * hidden_size
        self.gate = TopKGate(hidden_size, num_experts, k, capacity_factor,
                             eval_capacity_factor, min_capacity, noisy_gate_policy,
                             drop_tokens)
        self.experts = ExpertFFN(hidden_size, self.ffn_dim, num_experts)
        self.use_residual = use_residual  # PR-MoE residual expert (reference MoE)

    def init(self, rng):
        kg, ke, kr = jax.random.split(rng, 3)
        params = {"gate": self.gate.init(kg), "experts": self.experts.init(ke)}
        if self.use_residual:
            res = ExpertFFN(self.hidden_size, self.ffn_dim, 1)
            params["residual_mlp"] = res.init(kr)
            params["coefficient"] = jnp.zeros((self.hidden_size, 2))
        return params

    def logical_axes(self):
        axes = {"gate": {"wg": ("hidden", None)},
                "experts": ExpertFFN.logical_axes()}
        if self.use_residual:
            # single residual expert: leading dim 1 stays replicated
            axes["residual_mlp"] = {k: (None,) + v[1:]
                                    for k, v in ExpertFFN.logical_axes().items()}
            axes["coefficient"] = ("hidden", None)
        return axes

    def apply(self, params, x, *, train: bool = True, rng=None):
        b, t, m = x.shape
        tokens = x.reshape(b * t, m)
        tokens = _constrain(tokens, BATCH_AXES, None)
        l_aux, combine, dispatch, exp_counts = self.gate(
            params["gate"], tokens, train=train, rng=rng)
        # dispatch einsum: [S,M] x [S,E,C] -> [E,C,M]; resharding S-sharded →
        # E-sharded is the all_to_all (XLA inserts it over the expert axis)
        dispatched = jnp.einsum("sm,sec->ecm", tokens,
                                dispatch.astype(tokens.dtype))
        dispatched = _constrain(dispatched, EXPERT_AXIS, None, None)
        expert_out = self.experts.apply(params["experts"], dispatched)
        expert_out = _constrain(expert_out, EXPERT_AXIS, None, None)
        out = jnp.einsum("ecm,sec->sm", expert_out, combine.astype(expert_out.dtype))
        out = _constrain(out, BATCH_AXES, None)
        out = out.reshape(b, t, m)
        if self.use_residual:
            res = ExpertFFN(self.hidden_size, self.ffn_dim, 1)
            res_out = res.apply(params["residual_mlp"],
                                x.reshape(1, b * t, m)).reshape(b, t, m)
            coef = jax.nn.softmax(
                x.astype(jnp.float32) @ params["coefficient"], axis=-1)
            out = out * coef[..., 0:1].astype(out.dtype) + \
                res_out * coef[..., 1:2].astype(out.dtype)
        return out, l_aux, exp_counts


def split_params_into_different_moe_groups_for_optimizer(params, moe_paths=("experts",)):
    """Expert/non-expert param split (reference moe/utils.py:65) — returns
    (dense_tree, expert_tree) masks usable for per-group optimizer settings."""
    import jax

    def is_expert(path):
        return any(p in str(path) for p in moe_paths)

    leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    dense = [not is_expert(path) for path, _ in leaves]
    return treedef, dense
