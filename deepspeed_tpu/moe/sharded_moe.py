"""MoE gating + dispatch math.

TPU-native re-derivation of the reference's gating
(``deepspeed/moe/sharded_moe.py``: top1gating:179, top2gating:277,
TopKGate:343, MOELayer:473). Same semantics — softmax gate, capacity-factor
truncation, load-balancing aux loss, optional second expert — expressed as
static-shape einsums (SURVEY §7 hard-part #3: routing must stay static-shaped
to avoid recompiles; capacity padding + drop does that here exactly as in the
reference).

Dispatch/combine use the GShard formulation:
    dispatched[e,c,m] = Σ_s dispatch_mask[s,e,c] · x[s,m]
    out[s,m]         = Σ_{e,c} combine_weights[s,e,c] · expert_out[e,c,m]
With the token dim sharded over the batch axes and the expert dim sharded
over the 'expert' mesh axis, XLA lowers the dispatch einsum to the
all-to-all over ICI that the reference issues manually via its _AllToAll
autograd function (sharded_moe.py:90).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _one_hot(x, num_classes, dtype=jnp.float32):
    return jax.nn.one_hot(x, num_classes, dtype=dtype)


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def top1gating(logits: jax.Array, capacity_factor: float = 1.0,
               min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
               rng: Optional[jax.Array] = None, drop_tokens: bool = True,
               used_capacity: int = 0):
    """Top-1 gating (reference top1gating, sharded_moe.py:179).

    logits: [S, E]. Returns (l_aux, combine_weights [S,E,C], dispatch_mask
    [S,E,C] bool, exp_counts [E]).
    """
    s, e = logits.shape
    c = _capacity(s, e, capacity_factor, min_capacity) if drop_tokens else s

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if noisy_gate_policy == "RSample" and rng is not None:
        noisy = logits + jax.random.gumbel(rng, logits.shape, dtype=logits.dtype)
        indices1 = jnp.argmax(noisy, axis=-1)
    else:
        indices1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(indices1, e)  # [S, E]

    # load-balancing aux loss (Switch/GShard): E * Σ_e mean(gates_e)·mean(mask_e)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * e

    # position of each token within its chosen expert's capacity buffer
    locations1 = jnp.cumsum(mask1, axis=0) - mask1  # [S, E]
    mask1 = mask1 * (locations1 < c)
    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)

    gates1 = jnp.sum(gates * mask1, axis=-1)  # [S] gate value of kept tokens
    locations1_s = jnp.sum(locations1 * mask1, axis=-1).astype(jnp.int32)  # [S]

    combine = (gates1[:, None, None] * mask1[:, :, None] *
               _one_hot(locations1_s, c)[:, None, :])  # [S, E, C]
    dispatch = combine.astype(bool)
    return l_aux, combine, dispatch, exp_counts


def top2gating(logits: jax.Array, capacity_factor: float = 1.0,
               min_capacity: int = 4, rng: Optional[jax.Array] = None,
               drop_tokens: bool = True):
    """Top-2 gating (reference top2gating, sharded_moe.py:277): second expert
    chosen after masking the first; weights renormalised over the kept pair."""
    s, e = logits.shape
    c = _capacity(s, e, capacity_factor * 2.0, min_capacity) if drop_tokens else s

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    indices1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(indices1, e)
    logits_w_noise = logits.astype(jnp.float32)
    if rng is not None:
        logits_w_noise = logits_w_noise + jax.random.gumbel(rng, logits.shape)
    logits2 = jnp.where(mask1.astype(bool), -jnp.inf, logits_w_noise)
    indices2 = jnp.argmax(logits2, axis=-1)
    mask2 = _one_hot(indices2, e)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * e

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    # second-expert positions come after all first-expert tokens
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
    mask1 = mask1 * (locations1 < c)
    mask2 = mask2 * (locations2 < c)
    exp_counts = jnp.sum(mask1 + mask2, axis=0).astype(jnp.int32)

    locations1_s = jnp.sum(locations1 * mask1, axis=-1).astype(jnp.int32)
    locations2_s = jnp.sum(locations2 * mask2, axis=-1).astype(jnp.int32)

    gates1 = jnp.sum(gates * mask1, axis=-1)
    gates2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.clip(gates1 + gates2, 1e-9, None)
    gates1, gates2 = gates1 / denom, gates2 / denom

    combine1 = (gates1[:, None, None] * mask1[:, :, None] *
                _one_hot(locations1_s, c)[:, None, :])
    combine2 = (gates2[:, None, None] * mask2[:, :, None] *
                _one_hot(locations2_s, c)[:, None, :])
    combine = combine1 + combine2
    dispatch = combine.astype(bool)
    return l_aux, combine, dispatch, exp_counts


class TopKGate:
    """Gate module (reference TopKGate, sharded_moe.py:343)."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True):
        assert k in (1, 2), "only top-1 and top-2 gating supported (as reference)"
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    def init(self, rng):
        w = jax.random.normal(rng, (self.model_dim, self.num_experts),
                              jnp.float32) * (self.model_dim ** -0.5)
        return {"wg": w}

    def __call__(self, params, x, *, train: bool = True, rng=None):
        """x: [S, M] flattened tokens. Returns (l_aux, combine, dispatch, counts)."""
        logits = x.astype(jnp.float32) @ params["wg"]
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity,
                              self.noisy_gate_policy if train else None,
                              rng, self.drop_tokens)
        return top2gating(logits, cf, self.min_capacity,
                          rng if train else None, self.drop_tokens)
