from .layer import MoE, ExpertFFN, split_params_into_different_moe_groups_for_optimizer
from .sharded_moe import TopKGate, top1gating, top2gating
