"""Shared single-chip training-throughput harness for the sweep scripts.

One copy of the methodology (engine build → warmup/compile → best-of-N
short windows, fenced by `jax.device_get` because `block_until_ready`
under-synchronizes on the tunnel backend — see bench.py and the memory
notes). bench.py intentionally keeps its own inline copy so the driver can
run it with zero repo-internal imports beyond the package.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np


def train_tokens_per_sec(*, attn_impl: str, remat: bool, remat_policy,
                         batch: int, gas: int, seq: int = 1024,
                         steps: int = 8, windows: int = 3,
                         zero_stage: int = 0, loss_chunk: int = 0) -> float:
    """GPT-2-125M bf16 training throughput for one knob setting."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.utils import groups

    groups.reset()
    cfg = GPT2Config.gpt2_125m(max_seq_len=seq)
    if loss_chunk:
        cfg = dataclasses.replace(cfg, loss_chunk=loss_chunk)
    model = GPT2Model(cfg, remat=remat, remat_policy=remat_policy,
                      attn_impl=attn_impl)
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": batch * gas,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4,
                                                  "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
        "zero_optimization": {"stage": zero_stage},
    })
    rng = np.random.RandomState(0)

    def make_batch():
        ids = rng.randint(0, cfg.vocab_size,
                          size=(gas, batch, seq + 1)).astype(np.int32)
        return {"input_ids": ids[:, :, :-1], "labels": ids[:, :, 1:]}

    for _ in range(2):
        loss = engine.train_batch_from_stacked(make_batch())
    float(jax.device_get(loss))
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch_from_stacked(make_batch())
        float(jax.device_get(loss))
        best = min(best, time.perf_counter() - t0)
    return batch * gas * seq * steps / best


RESULT_TAG = "PHASE_RESULT:"


def emit_phase_result(result) -> None:
    import json

    print(RESULT_TAG + json.dumps(result), flush=True)


def run_phase_isolated(script_path, name, attempts=3, timeout=2400):
    """Run `python script_path --phase name` in fresh subprocesses until one
    succeeds (emits a RESULT_TAG line). The tunneled chip is shared: a
    transient RESOURCE_EXHAUSTED from a co-tenant's allocation poisons the
    whole JAX client, so in-process retries are useless — each attempt
    needs a clean process (see .claude/skills/verify/SKILL.md, axon
    gotchas)."""
    import json
    import subprocess
    import sys
    import time

    last = None
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, script_path, "--phase", name],
                capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            last = f"timeout after {timeout}s"
        else:
            for line in proc.stdout.splitlines():
                if line.startswith(RESULT_TAG):
                    out = json.loads(line[len(RESULT_TAG):])
                    print(f"[{name}] attempt {attempt}: ok", flush=True)
                    return out
            tail = (proc.stdout + proc.stderr)[-600:]
            last = (f"rc={proc.returncode}: "
                    f"{tail.splitlines()[-1] if tail else ''}")
        print(f"[{name}] attempt {attempt} failed: {last}", flush=True)
        if attempt + 1 < attempts:
            time.sleep(15)  # give the co-tenant's spike a beat to clear
    return {"error": f"all {attempts} attempts failed; last: {str(last)[:300]}"}
