#!/usr/bin/env python
"""Measured kernel-plan micro-autotuner (ISSUE 12 satellite; VERDICT
next-round #4).

Times candidate plans for the Pallas serving kernels on the RUNNING
backend and writes the committed plan artifact
(``AUTOTUNE_KERNELS_MEASURED.json``) that ops/autotune.py serves back
to the kernels at trace time:

  * ``decode_step``        — ``(bg, cs, vmem_mb, mha)`` per slot-paged
    geometry (ops/decode_step.fused_decode_step);
  * ``block_decode_step``  — ``(vmem_mb, mha)`` per block-paged
    geometry, bf16 AND quantized pools
    (ops/decode_step.fused_block_decode_step);
  * ``int8_matmul_dma``    — ``(bd, be)`` divisor tiles per weight
    shape (ops/int8_matmul.int8_matmul_dma).

The HAND-PICKED plan is always candidate 0 and the chosen plan is the
measured argmin, so a committed entry beats-or-ties the constants by
construction in its own windows (``us`` vs ``hand_us`` record both).
Timing methodology is bench.py's: per-candidate MEDIAN over several
best-of windows with block_until_ready fences — on a time-shared chip
one long window measures co-tenant load as much as the kernel.

Usage:
    python scripts/autotune_kernels.py --preset cpu-smoke   # sandbox
    python scripts/autotune_kernels.py --preset 125m        # on TPU
    python scripts/autotune_kernels.py --preset 7b          # on TPU

The cpu-smoke preset exists to keep the artifact format, the loading
path, and the beats-or-ties invariant exercised per-commit; interpret-
mode timings do NOT transfer to TPU, which is why ops/autotune.lookup
gates entries on the artifact's recorded backend.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops import autotune
from deepspeed_tpu.ops.decode_step import (_VMEM_LIMIT, _plan,
                                           fused_block_decode_step,
                                           fused_decode_step,
                                           supports, supports_block)
from deepspeed_tpu.ops.int8_matmul import (_aligned_divisors,
                                           _hand_dma_plan,
                                           int8_matmul_dma)
from deepspeed_tpu.serving.kv_quant import quantized_pool_like


def time_call(fn, *args, windows: int = 3, calls: int = 3) -> float:
    """Median over ``windows`` of (best-effort) per-call seconds, each
    window timing ``calls`` back-to-back invocations behind a
    block_until_ready fence. One untimed warmup call absorbs
    trace/compile."""
    jax.block_until_ready(fn(*args))
    samples = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(calls):
            out = fn(*args)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / calls)
    return statistics.median(samples)


def _us(seconds: float) -> float:
    return round(seconds * 1e6, 1)


# ---------------------------------------------------------------- decode
def tune_decode_step(b, hkv, s_max, dh, *, dtype=jnp.bfloat16,
                     windows=3, calls=3):
    """One slot-paged geometry: hand plan first, then a small
    (bg, cs, mha) grid. Returns (key, entry)."""
    assert supports(hkv, hkv, s_max, dh), (hkv, s_max, dh)
    itemsize = jnp.dtype(dtype).itemsize
    from deepspeed_tpu.ops.attention import kv_pack_factor

    pair = kv_pack_factor(dh)
    rng = np.random.RandomState(0)
    l = 1
    k_full = jnp.asarray(
        rng.randn(l, b, hkv, s_max // pair, dh * pair), dtype) * 0.1
    v_full = jnp.asarray(
        rng.randn(l, b, hkv, s_max // pair, dh * pair), dtype) * 0.1
    q = jnp.asarray(rng.randn(b, 1, hkv, dh), dtype)
    kn = jnp.asarray(rng.randn(b, 1, hkv, dh), dtype)
    vn = jnp.asarray(rng.randn(b, 1, hkv, dh), dtype)
    idx = jnp.asarray(rng.randint(s_max // 2, s_max - 8, size=(b,)),
                      jnp.int32)

    hand_bg, hand_cs = _plan(b, hkv, s_max, dh, itemsize)
    hand = {"bg": hand_bg, "cs": hand_cs, "vmem_mb": _VMEM_LIMIT >> 20,
            "mha": "mxu"}
    cands = [hand]
    bgs = sorted({g for g in (b, b // 2, 1) if g >= 1 and b % g == 0})
    css = [c for c in (128, 256, 512) if s_max % c == 0]
    for bg in bgs:
        for cs in css:
            for mha in ("mxu", "vpu"):
                c = {"bg": bg, "cs": cs, "vmem_mb": _VMEM_LIMIT >> 20,
                     "mha": mha}
                if c not in cands:
                    cands.append(c)

    results = []
    for cand in cands:
        fn = jax.jit(functools.partial(
            lambda q, k, v, kn, vn, idx, _p: fused_decode_step(
                q, k, v, kn, vn, 0, idx, plan=_p)[0], _p=cand))
        results.append((time_call(fn, q, k_full, v_full, kn, vn, idx,
                                  windows=windows, calls=calls), cand))
    results.sort(key=lambda r: r[0])
    best_t, best = results[0]
    hand_t = next(t for t, c in results if c == hand)
    entry = dict(best, us=_us(best_t), hand_us=_us(hand_t),
                 n_candidates=len(cands))
    return autotune.decode_key(b, hkv, s_max, dh, itemsize), entry


def tune_block_decode(b, hkv, bs, dh, *, dtype=jnp.bfloat16, kv_dtype=None,
                      mb=4, windows=3, calls=3):
    """One block-paged geometry (bf16 or quantized pool): the chunk
    size IS the pool block size, so only (vmem_mb, mha) are tunable."""
    assert supports_block(hkv, hkv, bs, dh), (hkv, bs, dh)
    from deepspeed_tpu.ops.attention import kv_pack_factor

    pair = kv_pack_factor(dh)
    rng = np.random.RandomState(0)
    n = b * mb + 1
    base = jnp.asarray(
        rng.randn(1, n + 1, hkv, bs // pair, dh * pair), dtype) * 0.1
    if kv_dtype is not None:
        k_pool = quantized_pool_like(base, dh, kv_dtype)
        v_pool = quantized_pool_like(base, dh, kv_dtype)
        itemsize = 1
    else:
        k_pool, v_pool = base, base + 0.01
        itemsize = jnp.dtype(dtype).itemsize
    q = jnp.asarray(rng.randn(b, 1, hkv, dh), dtype)
    kn = jnp.asarray(rng.randn(b, 1, hkv, dh), dtype)
    vn = jnp.asarray(rng.randn(b, 1, hkv, dh), dtype)
    idx = jnp.asarray(rng.randint(bs, mb * bs - 1, size=(b,)), jnp.int32)
    tbl = jnp.asarray(rng.permutation(n)[:b * mb].reshape(b, mb),
                      jnp.int32)

    hand = {"vmem_mb": _VMEM_LIMIT >> 20, "mha": "mxu"}
    cands = [hand] + [{"vmem_mb": v, "mha": m}
                      for v in (_VMEM_LIMIT >> 20, 64)
                      for m in ("mxu", "vpu")
                      if {"vmem_mb": v, "mha": m} != hand]
    results = []
    for cand in cands:
        fn = jax.jit(functools.partial(
            lambda q, k, v, kn, vn, idx, tbl, _p: fused_block_decode_step(
                q, k, v, kn, vn, 0, idx, tbl, plan=_p)[0], _p=cand))
        results.append((time_call(fn, q, k_pool, v_pool, kn, vn, idx, tbl,
                                  windows=windows, calls=calls), cand))
    results.sort(key=lambda r: r[0])
    best_t, best = results[0]
    hand_t = next(t for t, c in results if c == hand)
    entry = dict(best, us=_us(best_t), hand_us=_us(hand_t),
                 kv_dtype=kv_dtype or "compute", n_candidates=len(cands))
    return autotune.block_decode_key(b, hkv, bs, dh, itemsize), entry


# ------------------------------------------------------------ int8 matmul
def tune_int8_matmul(d, e, *, b=8, dtype=jnp.bfloat16, windows=3, calls=3):
    """One [D, E] int8 weight shape: hand plan + the distinct plans a
    few VMEM caps yield + a couple of narrower-row alternatives."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, d), dtype)
    q = jnp.asarray(rng.randint(-127, 128, size=(d, e)), jnp.int8)
    s = jnp.asarray(rng.rand(1, e) * 0.01 + 1e-3, jnp.float32)

    hand = _hand_dma_plan(d, e)
    assert hand is not None, (d, e)
    cands = [hand]
    for cap in (1_250_000, 2_500_000, 5_000_000):
        p = _hand_dma_plan(d, e, cap)
        if p is not None and p not in cands:
            cands.append(p)
    # narrower rows (half/quarter E) with fatter bd, if they divide
    for be in _aligned_divisors(e):
        if be in (hand[1],) or be * 4 < hand[1]:
            continue
        for bd in reversed(_aligned_divisors(d)):
            if bd * be <= 2_500_000:
                p = (bd, be)
                if p not in cands:
                    cands.append(p)
                break
        if len(cands) >= 6:
            break

    results = []
    for cand in cands:
        fn = functools.partial(int8_matmul_dma, plan=tuple(cand))
        results.append((time_call(fn, x, q, s, windows=windows,
                                  calls=calls), tuple(cand)))
    results.sort(key=lambda r: r[0])
    best_t, best = results[0]
    hand_t = next(t for t, c in results if c == tuple(hand))
    entry = {"bd": best[0], "be": best[1], "us": _us(best_t),
             "hand_us": _us(hand_t), "n_candidates": len(cands)}
    return autotune.matmul_key(d, e), entry


# ------------------------------------------------------------------ main
PRESETS = {
    # tiny interpret-mode shapes: keeps the artifact format + loading
    # path + beats-or-ties invariant exercised on the CPU sandbox
    "cpu-smoke": {
        "decode": [(4, 4, 256, 64)],
        "block": [(2, 4, 16, 64, None), (2, 4, 16, 64, "int8")],
        "matmul": [(256, 512)],
        "windows": 2, "calls": 2,
    },
    # GPT-2-125M serving geometry (B=8 decode, prompt 512 cache 640)
    "125m": {
        "decode": [(8, 12, 640, 64), (1, 12, 640, 64)],
        "block": [(8, 12, 128, 64, None), (8, 12, 128, 64, "int8"),
                  (8, 12, 128, 64, "fp8")],
        "matmul": [(768, 2304), (768, 768), (768, 3072), (3072, 768)],
        "windows": 5, "calls": 8,
    },
    # 6.7B geometry (Dh=128, LLaMA-ish MLP dims)
    "7b": {
        "decode": [(1, 32, 2048, 128), (8, 32, 2048, 128)],
        "block": [(8, 32, 128, 128, None), (8, 32, 128, 128, "int8"),
                  (8, 32, 128, 128, "fp8")],
        "matmul": [(4096, 12288), (4096, 4096), (4096, 11008),
                   (11008, 4096)],
        "windows": 5, "calls": 8,
    },
}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=sorted(PRESETS), default=None,
                    help="shape set (default: cpu-smoke off-TPU, 125m on)")
    # artifact_path() honors DSTPU_KERNEL_PLANS, whose documented
    # empty-string value DISABLES lookups — never let it eat the write
    ap.add_argument("--out",
                    default=autotune.artifact_path()
                    or autotune._REPO_ARTIFACT)
    args = ap.parse_args(argv)
    backend = jax.default_backend()
    preset = args.preset or ("125m" if backend == "tpu" else "cpu-smoke")
    cfg = PRESETS[preset]
    w, c = cfg["windows"], cfg["calls"]

    plans = {"decode_step": {}, "block_decode_step": {},
             "int8_matmul_dma": {}}
    for (b, hkv, s_max, dh) in cfg["decode"]:
        key, ent = tune_decode_step(b, hkv, s_max, dh, windows=w, calls=c)
        plans["decode_step"][key] = ent
        print(f"decode_step {key}: {ent}")
    for (b, hkv, bs, dh, kvd) in cfg["block"]:
        key, ent = tune_block_decode(b, hkv, bs, dh, kv_dtype=kvd,
                                     windows=w, calls=c)
        # quantized and bf16 pools share a key only if itemsizes match;
        # keep the better-measured entry on collision
        old = plans["block_decode_step"].get(key)
        if old is None or ent["us"] < old["us"]:
            plans["block_decode_step"][key] = ent
        print(f"block_decode_step {key}: {ent}")
    for (d, e) in cfg["matmul"]:
        key, ent = tune_int8_matmul(d, e, windows=w, calls=c)
        plans["int8_matmul_dma"][key] = ent
        print(f"int8_matmul_dma {key}: {ent}")

    art = {
        "metric": "kernel_plan_autotune",
        "backend": backend,
        "device": str(jax.devices()[0].device_kind),
        "preset": preset,
        "method": f"median_of_{w}x{c}call_windows_vs_hand_candidate0",
        "plans": plans,
    }
    with open(args.out, "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
