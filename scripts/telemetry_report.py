#!/usr/bin/env python
"""Render a telemetry JSONL run into a human summary.

Usage:
    python scripts/telemetry_report.py RUN.jsonl [--json]

Input is the file produced by the telemetry subsystem (ISSUE 3): the
engine's periodic registry snapshots (``telemetry.jsonl_path`` config key),
the JSONL monitor backend's scalar stream (``jsonl_monitor`` section), and
discrete events (checkpoint saves, corruption fallbacks, elastic
restarts) — any mix of the three record kinds in one file.

Sections:
  counters    — final values from the newest snapshot
  gauges      — final values (device step time, MFU, memory, occupancy...)
  histograms  — count/mean/p50/p95/p99/max per latency histogram
  scalars     — per-tag last/min/max/mean over the monitor scalar stream
  events      — occurrence counts per event name
  spans       — span-graph critical paths (ISSUE 11): per-request
                p50/p95 time + fraction in queue/prefill/decode/
                swapped/failover, reconstructed from "span" records
  attribution — per-program roofline (ISSUE 11): flops/bytes/intensity,
                achieved vs attainable TFLOPs and the binding roof, from
                "attribution" records
  slo         — SLO scheduling view (ISSUE 8) merged with the SLO
                control plane (ISSUE 13): error-budget consumption per
                SLI, burn-rate timeline stats per rule, and the
                fired/resolved alert sequence from "slo_eval" +
                slo/alert_* event records
  tenants     — per-tenant usage table (ISSUE 13): prompt/decode
                tokens, prefill computed vs saved, KV block-seconds,
                preemptions/sheds, TTFT/TPOT p50 from the
                serving/tenant/<t>/* metrics
  postmortem  — incident summary from a flight-recorder dump
                (``--postmortem DUMP.json``, or pass the dump file as
                the positional path): trigger, affected requests and
                tenants, alert state at the dump instant, record-
                completeness verdict

``--json`` emits the aggregate as one JSON object instead of tables
(machine-readable; the smoke test uses it). Stdlib only — runs anywhere.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import OrderedDict


def load_records(path):
    """Tolerant JSONL reader, matching telemetry.sink.read_jsonl
    (ISSUE 9 satellite): lines torn by a crash mid-write — truncated
    JSON, bytes cut inside a UTF-8 sequence, non-object values — are
    skipped and COUNTED, never raised. The report renders the artifact
    that survives a crash, so it must not fail on crash damage.
    Returns ``(records, n_bad_lines)``."""
    out = []
    bad = 0
    with open(path, "rb") as f:
        for raw in f:
            line = raw.decode("utf-8", errors="replace").strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict):
                out.append(rec)
            else:
                bad += 1
    return out, bad


def aggregate(records, n_bad_lines=0, postmortem=None):
    last_snapshot = None
    scalars = OrderedDict()   # tag -> stats dict
    events = OrderedDict()    # name -> {count, last_fields}
    spans = []                # raw span records, arrival order
    attributions = OrderedDict()   # scope -> last program table
    slo_evals = []            # SLO-engine burn-rate timeline (ISSUE 13)
    elastic_events = []       # autoscaler + pool-membership events (ISSUE 16)
    for rec in records:
        kind = rec.get("kind")
        if kind == "snapshot":
            last_snapshot = rec
        elif kind == "span":
            spans.append(rec)
        elif kind == "slo_eval":
            slo_evals.append(rec)
        elif kind == "attribution":
            attributions[rec.get("scope", "?")] = rec.get("programs", {})
        elif kind == "scalar":
            tag = rec.get("tag", "?")
            try:
                v = float(rec.get("value"))
            except (TypeError, ValueError):
                continue
            s = scalars.setdefault(tag, {
                "count": 0, "sum": 0.0, "min": v, "max": v,
                "last": v, "last_step": rec.get("step")})
            s["count"] += 1
            s["sum"] += v
            s["min"] = min(s["min"], v)
            s["max"] = max(s["max"], v)
            s["last"] = v
            s["last_step"] = rec.get("step")
        elif kind == "event":
            name = rec.get("name", "?")
            e = events.setdefault(name, {"count": 0, "last": {}})
            e["count"] += 1
            e["last"] = {k: v for k, v in rec.items()
                         if k not in ("kind", "name", "ts")}
            if name in ("fabric/autoscale", "fabric/replica_added",
                        "fabric/replica_draining",
                        "fabric/replica_removed"):
                elastic_events.append(rec)
    for s in scalars.values():
        s["mean"] = s["sum"] / s["count"] if s["count"] else 0.0
    metrics = (last_snapshot or {}).get("metrics", {})
    return {
        "snapshot_step": (last_snapshot or {}).get("step"),
        "counters": metrics.get("counters", {}),
        "gauges": metrics.get("gauges", {}),
        "histograms": metrics.get("histograms", {}),
        "scalars": scalars,
        "events": events,
        "speculation": _speculation_summary(metrics),
        "prefix_cache": _prefix_cache_summary(metrics),
        "slo": _slo_summary(metrics, slo_evals, events),
        "tenants": _tenants_summary(metrics),
        "fabric": _fabric_summary(metrics),
        "autoscaler": _autoscaler_summary(metrics, elastic_events),
        "resilience": _resilience_summary(metrics),
        "spans": _spans_summary(spans),
        "attribution": _attribution_summary(attributions),
        "postmortem": _postmortem_summary(postmortem),
        "n_records": len(records),
        "n_bad_lines": n_bad_lines,
    }


def _spans_summary(spans):
    """Derived span-graph view (ISSUE 11): per-request critical-path
    breakdown — p50/p95 of absolute time and of the FRACTION of each
    request's life spent in queue/prefill/decode/swapped/failover —
    plus per-span-name counts. Stdlib reimplementation of
    telemetry.spans.trace_summaries/aggregate_phase_stats so the report
    stays runnable anywhere. Empty dict when the run recorded no
    spans."""
    if not spans:
        return {}
    phase_of = {"queue_wait": "queue", "router_queue": "queue",
                "prefill_chunk": "prefill", "decode_segment": "decode",
                "swap_out": "swapped", "swapped": "swapped",
                "swap_in": "swapped", "failover": "failover"}
    phases = ("queue", "prefill", "decode", "swapped", "failover")
    by_name = OrderedDict()
    by_trace = OrderedDict()
    for s in spans:
        name = s.get("name", "?")
        by_name[name] = by_name.get(name, 0) + 1
        by_trace.setdefault(s.get("trace"), []).append(s)
    requests = []
    for group in by_trace.values():
        roots = [s for s in group if s.get("name") == "request"
                 and s.get("end") is not None]
        if not roots:
            continue
        root = roots[0]
        total = max(root["end"] - root.get("start", 0.0), 0.0)
        ph = {p: 0.0 for p in phases}
        for s in group:
            p = phase_of.get(s.get("name"))
            if p is None or s.get("end") is None:
                continue
            ph[p] += max(s["end"] - s.get("start", 0.0), 0.0)
        requests.append((total, ph))
    out = {"n_spans": len(spans), "span_counts": dict(by_name),
           "n_requests": len(requests)}
    if not requests:
        return out

    def pct(xs, p):
        xs = sorted(xs)
        return xs[min(int(len(xs) * p), len(xs) - 1)]

    totals = [t for t, _ in requests]
    out["total_ms"] = {"p50": round(pct(totals, 0.5) * 1e3, 3),
                       "p95": round(pct(totals, 0.95) * 1e3, 3)}
    for p in phases:
        ab = [ph[p] for _, ph in requests]
        if not any(ab):
            continue
        fr = [(ph[p] / t if t > 0 else 0.0) for t, ph in requests]
        out[p] = {"frac_p50": round(pct(fr, 0.5), 4),
                  "frac_p95": round(pct(fr, 0.95), 4),
                  "ms_p50": round(pct(ab, 0.5) * 1e3, 3),
                  "ms_p95": round(pct(ab, 0.95) * 1e3, 3)}
    return out


def _attribution_summary(attributions):
    """Per-program roofline tables (ISSUE 11), keyed by scope (serving
    / train): the last "attribution" record per scope wins — it carries
    the most wall-time context. Empty dict when the run recorded
    none."""
    return {scope: table for scope, table in attributions.items()
            if table}


def _speculation_summary(metrics):
    """Derived speculative-decoding view (ISSUE 4) over the serving
    engine's raw counters/gauges/histograms: acceptance rate, committed
    tokens per verify step, and drafting's share of the decode wall.
    Empty dict when the run never speculated."""
    counters = metrics.get("counters", {})
    drafted = counters.get("serving/spec_drafted_tokens")
    if not drafted:
        return {}
    accepted = counters.get("serving/spec_accepted_tokens", 0)
    out = {
        "drafted_tokens": drafted,
        "accepted_tokens": accepted,
        "acceptance_rate": round(accepted / drafted, 4),
        "verify_steps": counters.get("serving/spec_verify_steps"),
    }
    gauges = metrics.get("gauges", {})
    for key, name in (("serving/spec_tokens_per_slot_step",
                       "tokens_per_slot_step"),
                      ("serving/spec_draft_overhead_frac",
                       "draft_overhead_frac"),
                      ("serving/spec_acceptance_rate",
                       "acceptance_rate_gauge")):
        if gauges.get(key) is not None:
            out[name] = gauges[key]
    h = metrics.get("histograms", {}).get(
        "serving/accepted_tokens_per_step")
    if h and h.get("count"):
        out["accepted_tokens_per_step_p50"] = h.get("p50")
        out["accepted_tokens_per_step_max"] = h.get("max")
    return out


def _prefix_cache_summary(metrics):
    """Derived prefix-cache view (ISSUE 6) over the serving engine's raw
    counters/gauges: tokens served from the radix index vs prefilled,
    the resulting hit rate, COW fork / LRU eviction counts, and pool
    occupancy. Empty dict when the run never enabled the cache."""
    counters = metrics.get("counters", {})
    gauges = metrics.get("gauges", {})
    hit = counters.get("serving/prefix_hit_tokens")
    miss = counters.get("serving/prefix_miss_tokens")
    if hit is None and miss is None \
            and gauges.get("serving/prefix_hit_rate") is None:
        return {}
    hit, miss = hit or 0, miss or 0
    out = {
        "hit_tokens": hit,
        "miss_tokens": miss,
        "hit_rate": round(hit / (hit + miss), 4) if hit + miss else 0.0,
        "blocks_cowed": counters.get("serving/blocks_cowed", 0),
        "blocks_evicted": counters.get("serving/blocks_evicted", 0),
    }
    for key, name in (("serving/prefix_hit_rate", "hit_rate_gauge"),
                      ("serving/prefix_pool_occupancy", "pool_occupancy"),
                      ("serving/prefix_cached_blocks", "cached_blocks")):
        if gauges.get(key) is not None:
            out[name] = gauges[key]
    return out


def _slo_summary(metrics, slo_evals=None, events=None):
    """Derived SLO view: the ISSUE-8 scheduling actions (chunked
    prefill, TPOT-guard deferrals, preemptions, host swap traffic,
    per-class latency tails) merged with the ISSUE-13 control plane —
    error-budget consumption per SLI, per-rule burn-rate timeline
    stats over the "slo_eval" records, and the alert transition
    sequence. Empty dict when the run used neither."""
    base = _slo_sched_summary(metrics)
    plane = _slo_plane_summary(slo_evals or [], events or {})
    base.update(plane)
    return base


def _slo_plane_summary(slo_evals, events):
    """SLO-engine fields (ISSUE 13). Empty dict when the run recorded
    no slo_eval records and no alert events."""
    out = {}
    fired = events.get("slo/alert_fired", {}).get("count", 0)
    resolved = events.get("slo/alert_resolved", {}).get("count", 0)
    if not slo_evals and not fired and not resolved:
        return out
    if fired or resolved:
        out["alerts_fired"] = fired
        out["alerts_resolved"] = resolved
    if not slo_evals:
        return out
    out["slo_evaluations"] = len(slo_evals)
    last = slo_evals[-1]
    for sli, consumed in sorted(
            (last.get("budget_consumed") or {}).items()):
        out[f"budget_consumed/{sli}"] = consumed
    # per-rule burn timeline: max observed burn + evaluations spent
    # firing — the compressed "when and how hard did it burn" view
    rules = {}
    for rec in slo_evals:
        for rule, st in (rec.get("rules") or {}).items():
            if not isinstance(st, dict):
                continue
            r = rules.setdefault(rule, {"max_burn_short": 0.0,
                                        "max_burn_long": 0.0,
                                        "evals_firing": 0})
            try:
                r["max_burn_short"] = max(r["max_burn_short"],
                                          float(st.get("burn_short", 0)))
                r["max_burn_long"] = max(r["max_burn_long"],
                                         float(st.get("burn_long", 0)))
            except (TypeError, ValueError):
                pass
            if st.get("firing"):
                r["evals_firing"] += 1
    for rule, r in sorted(rules.items()):
        out[f"rule/{rule}"] = {
            "max_burn_short": round(r["max_burn_short"], 2),
            "max_burn_long": round(r["max_burn_long"], 2),
            "evals_firing": r["evals_firing"]}
    return out


def _tenants_summary(metrics):
    """Per-tenant usage table (ISSUE 13) over the
    ``serving/tenant/<t>/<metric>`` namespace in the newest snapshot.
    Empty dict when the run carried no tenant accounting."""
    out = OrderedDict()
    prefix = "serving/tenant/"
    for name, v in sorted(metrics.get("counters", {}).items()):
        if not name.startswith(prefix):
            continue
        rest = name[len(prefix):]
        tenant, _, metric = rest.rpartition("/")
        if not tenant:
            continue
        row = out.setdefault(tenant, OrderedDict())
        row[metric] = round(v, 3) if isinstance(v, float) else v
    for name, h in sorted(metrics.get("histograms", {}).items()):
        if not name.startswith(prefix) or not h.get("count"):
            continue
        rest = name[len(prefix):]
        tenant, _, metric = rest.rpartition("/")
        if not tenant:
            continue
        row = out.setdefault(tenant, OrderedDict())
        row[f"{metric}_p50"] = h.get("p50")
        row[f"{metric}_p99"] = h.get("p99")
    return out


def _postmortem_summary(dump):
    """Incident summary from a flight-recorder dump payload (ISSUE 13):
    what tripped, which requests/tenants were in the blast radius, the
    alert state at the dump instant, and whether the record itself is
    complete. Empty dict when no dump was given."""
    if not isinstance(dump, dict) or dump.get("kind") != "flight_dump":
        return {}
    out = OrderedDict()
    out["trigger"] = dump.get("reason", "?")
    ctx = dump.get("context") or {}
    for k, v in sorted(ctx.items()):
        out[f"context/{k}"] = v
    spans = [s for s in dump.get("spans", []) if isinstance(s, dict)]
    events = [e for e in dump.get("events", []) if isinstance(e, dict)]
    out["window_spans"] = len(spans)
    out["window_events"] = len(events)
    rids = sorted({a.get("rid") for s in spans
                   for a in [s.get("attrs") or {}] if a.get("rid")
                   is not None})
    if rids:
        out["requests_in_window"] = len(rids)
        out["request_ids"] = rids[:20]
    counters = (dump.get("metrics") or {}).get("counters", {})
    tenants = sorted({name.split("/")[2]
                      for name in counters
                      if name.startswith("serving/tenant/")
                      and len(name.split("/")) > 3})
    if tenants:
        out["tenants"] = tenants
    alerts = [a for a in dump.get("alerts", []) if isinstance(a, dict)]
    firing = []
    budget = {}
    for rec in alerts:
        for rule, st in (rec.get("rules") or {}).items():
            if isinstance(st, dict) and st.get("firing") \
                    and rule not in firing:
                firing.append(rule)
        budget.update(rec.get("budget_consumed") or {})
    if firing:
        out["rules_fired_in_window"] = firing
    for sli, consumed in sorted(budget.items()):
        out[f"budget_consumed/{sli}"] = consumed
    ev_names = OrderedDict()
    for e in events:
        n = e.get("name", e.get("kind", "?"))
        ev_names[n] = ev_names.get(n, 0) + 1
    if ev_names:
        out["event_counts"] = dict(ev_names)
    dropped = dump.get("upstream_dropped") or {}
    out["complete"] = bool(dump.get("complete", False))
    if dropped.get("spans") or dropped.get("events"):
        out["upstream_dropped"] = dropped
    return out


def _slo_sched_summary(metrics):
    """The ISSUE-8 half of the slo section: scheduling actions + the
    per-priority-class latency tails. Empty dict when the run never
    used the SLO scheduling machinery."""
    counters = metrics.get("counters", {})
    hists = metrics.get("histograms", {})
    per_class = {name: h for name, h in hists.items()
                 if (name.startswith("serving/ttft_ms/p")
                     or name.startswith("serving/tpot_ms/p"))
                 and h.get("count")}
    keys = ("serving/prefill_chunks", "serving/preemptions",
            "serving/slo_deferred_steps", "serving/swapped_blocks_out",
            "serving/swapped_blocks_in")
    # the engine records per-class histograms unconditionally (every
    # request has a class — p0 by default), so class histograms only
    # signal SLO usage when a NON-default class appears; otherwise a
    # plain serving run would grow a noise section
    multi_class = any(not name.endswith("/p0") for name in per_class)
    if not any(counters.get(k) for k in keys) and not multi_class:
        return {}
    out = {}
    for k in keys:
        if counters.get(k) is not None:
            out[k.split("/", 1)[1]] = counters[k]
    gauges = metrics.get("gauges", {})
    for key, name in (("serving/swap_buffer_bytes", "swap_buffer_bytes"),
                      ("serving/swap_buffer_peak_bytes",
                       "swap_buffer_peak_bytes")):
        if gauges.get(key) is not None:
            out[name] = gauges[key]
    for name, h in sorted(per_class.items()):
        out[name.split("/", 1)[1]] = {
            "count": h.get("count"), "p50": h.get("p50"),
            "p95": h.get("p95"), "p99": h.get("p99")}
    return out


def _fabric_summary(metrics):
    """Derived multi-replica fabric view (ISSUE 9) over the router's
    raw counters/gauges/histograms: dispatch/failover/retry/shed/crash
    counters, the failover-latency tail, and the per-replica health
    gauges (load, queue depth, free slots, breaker state). Empty dict
    when the run never used the fabric."""
    counters = {k: v for k, v in metrics.get("counters", {}).items()
                if k.startswith("fabric/")}
    gauges = {k: v for k, v in metrics.get("gauges", {}).items()
              if k.startswith("fabric/")}
    hists = {k: h for k, h in metrics.get("histograms", {}).items()
             if k.startswith("fabric/") and h.get("count")}
    if not counters and not gauges and not hists:
        return {}
    out = {}
    for k, v in sorted(counters.items()):
        out[k.split("/", 1)[1]] = v
    for k, v in sorted(gauges.items()):
        out[k.split("/", 1)[1]] = v
    for k, h in sorted(hists.items()):
        out[k.split("/", 1)[1]] = {
            "count": h.get("count"), "p50": h.get("p50"),
            "p95": h.get("p95"), "p99": h.get("p99")}
    return out


def _autoscaler_summary(metrics, elastic_events):
    """Derived elastic-autoscaling view (ISSUE 16) pinned from the twin
    (or live) JSONL stream: the full scale-decision timeline WITH the
    evidence that justified each decision, the pool-size series, and
    the graceful-drain duration tail. Crash-tolerant like everything
    else here: torn or field-less event records degrade to '-' cells,
    never to a raised exception. Empty dict when the run never used
    the elastic pool."""
    counters = {k: v for k, v in metrics.get("counters", {}).items()
                if k.startswith("fabric/autoscale")
                or k in ("fabric/replicas_added", "fabric/replicas_removed",
                         "fabric/drain_redispatches")}
    if not counters and not elastic_events:
        return {}
    out = {}
    for k, v in sorted(counters.items()):
        out[k.split("/", 1)[1]] = v

    def _num(v):
        try:
            return float(v)
        except (TypeError, ValueError):
            return None

    decisions, pool_series, drains = [], [], []
    for rec in elastic_events:
        name, t = rec.get("name"), _num(rec.get("t"))
        if name == "fabric/autoscale":
            evidence = {k: rec[k] for k in
                        ("queue_depth", "shed_delta", "firing_pages",
                         "firing_warns", "budget_spent") if k in rec}
            decisions.append({
                "t": t, "action": rec.get("action", "?"),
                "reason": rec.get("reason", "?"),
                "replica": rec.get("replica"),
                "pool": f"{rec.get('pool_before', '?')}"
                        f"->{rec.get('pool_after', '?')}",
                "evidence": evidence})
            continue
        pool = _num(rec.get("pool_size"))
        if pool is not None and t is not None and \
                name in ("fabric/replica_added", "fabric/replica_removed"):
            pool_series.append((t, int(pool)))
        if name == "fabric/replica_removed":
            d = _num(rec.get("duration_ms"))
            if d is not None:
                drains.append(d)
    if decisions:
        out["decisions"] = decisions
    if pool_series:
        out["pool_size_series"] = sorted(pool_series)
    if drains:
        drains.sort()

        def pct(p):
            return round(drains[min(int(len(drains) * p),
                                    len(drains) - 1)], 3)

        out["drain_ms"] = {"count": len(drains), "p50": pct(0.5),
                           "p95": pct(0.95), "max": round(drains[-1], 3)}
    return out


def _resilience_summary(metrics):
    """Derived training-resilience view (ISSUE 10) over the engine's raw
    counters/histograms: anomalies by class (nonfinite/overflow/spike/
    divergence/sdc/replay), rewinds and skipped batches, SDC audit and
    step-replay outcomes, and the recovery-latency tail. Empty dict when
    the run never armed the sentinel."""
    counters = {k: v for k, v in metrics.get("counters", {}).items()
                if k.startswith("resilience/")}
    gauges = {k: v for k, v in metrics.get("gauges", {}).items()
              if k.startswith("resilience/")
              or k == "train/nonfinite_skipped_steps"}
    hists = {k: h for k, h in metrics.get("histograms", {}).items()
             if k.startswith("resilience/") and h.get("count")}
    if not counters and not gauges and not hists:
        return {}
    out = {}
    anomalies = {k.split("anomalies_", 1)[1]: v
                 for k, v in counters.items()
                 if k.startswith("resilience/anomalies_")}
    if anomalies:
        out["anomalies_total"] = sum(anomalies.values())
    for k, v in sorted(counters.items()):
        out[k.split("/", 1)[1]] = v
    for k, v in sorted(gauges.items()):
        out[k.split("/", 1)[1]] = v
    for k, h in sorted(hists.items()):
        out[k.split("/", 1)[1]] = {
            "count": h.get("count"), "p50": h.get("p50"),
            "p95": h.get("p95"), "p99": h.get("p99"),
            "max": h.get("max")}
    return out


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1e6 or abs(v) < 1e-3:
            return f"{v:.3e}"
        return f"{v:,.3f}".rstrip("0").rstrip(".")
    return str(v)


def _table(title, header, rows, out):
    if not rows:
        return
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              for i, h in enumerate(header)]
    out.append(f"\n== {title} ==")
    out.append("  ".join(str(h).ljust(w) for h, w in zip(header, widths)))
    out.append("  ".join("-" * w for w in widths))
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))


def render(agg):
    out = [f"telemetry report — {agg['n_records']} records"
           + (f", last snapshot at step {agg['snapshot_step']}"
              if agg["snapshot_step"] is not None else "")
           + (f", {agg['n_bad_lines']} corrupt line(s) skipped"
              if agg.get("n_bad_lines") else "")]
    _table("counters", ("counter", "value"),
           [(k, _fmt(v)) for k, v in sorted(agg["counters"].items())], out)
    _table("gauges", ("gauge", "value"),
           [(k, _fmt(v)) for k, v in sorted(agg["gauges"].items())], out)
    hrows = []
    for k, h in sorted(agg["histograms"].items()):
        if not h.get("count"):
            continue
        hrows.append((k, h["count"], _fmt(h.get("mean")), _fmt(h.get("p50")),
                      _fmt(h.get("p95")), _fmt(h.get("p99")),
                      _fmt(h.get("max"))))
    _table("histograms", ("histogram", "count", "mean", "p50", "p95", "p99",
                          "max"), hrows, out)
    srows = [(k, s["count"], _fmt(s["last"]), _fmt(s["min"]), _fmt(s["mean"]),
              _fmt(s["max"]))
             for k, s in agg["scalars"].items()]
    _table("scalars", ("tag", "n", "last", "min", "mean", "max"), srows, out)
    _table("speculation", ("metric", "value"),
           [(k, _fmt(v)) for k, v in agg.get("speculation", {}).items()],
           out)
    _table("prefix_cache", ("metric", "value"),
           [(k, _fmt(v)) for k, v in agg.get("prefix_cache", {}).items()],
           out)
    _table("slo", ("metric", "value"),
           [(k, _fmt(v) if not isinstance(v, dict) else
             " ".join(f"{kk}={_fmt(vv)}" for kk, vv in v.items()))
            for k, v in agg.get("slo", {}).items()], out)
    _table("tenants", ("tenant", "usage"),
           [(t, " ".join(f"{kk}={_fmt(vv)}" for kk, vv in row.items()))
            for t, row in agg.get("tenants", {}).items()], out)
    _table("postmortem", ("field", "value"),
           [(k, _fmt(v) if not isinstance(v, (dict, list)) else
             json.dumps(v, default=str)[:80])
            for k, v in agg.get("postmortem", {}).items()], out)
    _table("fabric", ("metric", "value"),
           [(k, _fmt(v) if not isinstance(v, dict) else
             " ".join(f"{kk}={_fmt(vv)}" for kk, vv in v.items()))
            for k, v in agg.get("fabric", {}).items()], out)
    asc = dict(agg.get("autoscaler", {}))
    asc_decisions = asc.pop("decisions", [])
    asc_pool = asc.pop("pool_size_series", [])
    if asc_pool:
        asc["pool_size_series"] = " ".join(
            f"{_fmt(t)}:{n}" for t, n in asc_pool)
    _table("autoscaler", ("metric", "value"),
           [(k, _fmt(v) if not isinstance(v, dict) else
             " ".join(f"{kk}={_fmt(vv)}" for kk, vv in v.items()))
            for k, v in asc.items()], out)
    _table("autoscaler decisions",
           ("t", "action", "reason", "replica", "pool", "evidence"),
           [(_fmt(d.get("t")), d.get("action", "?"), d.get("reason", "?"),
             d.get("replica") or "-", d.get("pool", "?"),
             json.dumps(d.get("evidence", {}), default=str)[:70])
            for d in asc_decisions], out)
    _table("resilience", ("metric", "value"),
           [(k, _fmt(v) if not isinstance(v, dict) else
             " ".join(f"{kk}={_fmt(vv)}" for kk, vv in v.items()))
            for k, v in agg.get("resilience", {}).items()], out)
    _table("spans", ("metric", "value"),
           [(k, _fmt(v) if not isinstance(v, dict) else
             " ".join(f"{kk}={_fmt(vv)}" for kk, vv in v.items()))
            for k, v in agg.get("spans", {}).items()], out)
    for scope, table in agg.get("attribution", {}).items():
        arows = []
        for name, row in sorted(table.items()):
            if not isinstance(row, dict):
                continue
            arows.append((name, _fmt(row.get("flops")),
                          _fmt(row.get("bytes_accessed")),
                          _fmt(row.get("intensity_flops_per_byte")),
                          _fmt(row.get("calls")),
                          _fmt(row.get("mean_wall_ms")),
                          _fmt(row.get("achieved_tflops")),
                          _fmt(row.get("attainable_tflops")),
                          _fmt(row.get("achieved_vs_attainable")),
                          _fmt(row.get("bound"))))
        _table(f"attribution ({scope})",
               ("program", "flops", "bytes", "flops/byte", "calls",
                "wall_ms", "achieved_tf", "attainable_tf",
                "ach/att", "bound"), arows, out)
    erows = [(k, e["count"],
              json.dumps(e["last"], default=str)[:60])
             for k, e in agg["events"].items()]
    _table("events", ("event", "count", "last"), erows, out)
    return "\n".join(out)


def load_flight_dump(path):
    """Parse a flight-recorder dump JSON; returns the payload dict or
    None when the file is not a dump (crash-tolerant: unreadable /
    corrupt files degrade to None, never raise — the postmortem tool
    must not fail on the artifact needed to debug the crash)."""
    try:
        with open(path, "rb") as f:
            payload = json.loads(
                f.read().decode("utf-8", errors="replace"))
    except (OSError, ValueError):
        return None
    if isinstance(payload, dict) and payload.get("kind") == "flight_dump":
        return payload
    return None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="telemetry JSONL file, or a "
                                "flight-recorder dump JSON")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregate as JSON instead of tables")
    p.add_argument("--postmortem", default=None, metavar="DUMP",
                   help="flight-recorder dump JSON rendered as the "
                        "postmortem section (ISSUE 13)")
    args = p.parse_args(argv)
    dump = load_flight_dump(args.postmortem) if args.postmortem else None
    if args.postmortem and dump is None:
        print(f"telemetry_report: --postmortem {args.postmortem} is not "
              f"a readable flight-recorder dump", file=sys.stderr)
        return 2
    # the positional path may itself be a dump: render the incident's
    # embedded window instead of demanding a separate JSONL
    primary_dump = load_flight_dump(args.path)
    if primary_dump is not None:
        records = (primary_dump.get("spans", [])
                   + primary_dump.get("events", [])
                   + primary_dump.get("snapshots", [])
                   + primary_dump.get("alerts", []))
        records = [r for r in records if isinstance(r, dict)]
        n_bad = 0
        dump = dump or primary_dump
    else:
        try:
            records, n_bad = load_records(args.path)
        except OSError as e:
            print(f"telemetry_report: cannot read {args.path}: {e}",
                  file=sys.stderr)
            return 2
    agg = aggregate(records, n_bad_lines=n_bad, postmortem=dump)
    if args.json:
        print(json.dumps(agg, indent=2, default=str))
    else:
        print(render(agg))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
