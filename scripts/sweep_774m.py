"""Find the champion single-chip GPT-2-774M training config (VERDICT r5
ask #4: a headline config big enough to clear 55% MFU-vs-attainable).

Each candidate runs in a FRESH subprocess (RESOURCE_EXHAUSTED poisons the
client — run_7b.py lesson)."""
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

TAG = "RESULT:"


def run_one(mb, gas, remat, policy, gad="fp32", loss_chunk=0, steps=4,
            windows=3):
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.utils import groups

    groups.reset()
    cfg = GPT2Config.gpt2_774m(loss_chunk=loss_chunk)
    seq = 1024
    model = GPT2Model(cfg, attn_impl="flash", remat=bool(remat),
                      remat_policy=policy if remat else None)
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": mb * gas,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
        "zero_optimization": {"stage": 0},
        "data_types": {"grad_accum_dtype": gad},
    })
    rng = np.random.RandomState(0)

    def make_batch():
        ids = rng.randint(0, cfg.vocab_size,
                          size=(gas, mb, seq + 1)).astype(np.int32)
        return {"input_ids": ids[:, :, :-1], "labels": ids[:, :, 1:]}

    for _ in range(2):
        loss = engine.train_batch_from_stacked(make_batch())
    float(jax.device_get(loss))
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch_from_stacked(make_batch())
        float(jax.device_get(loss))
        best = min(best, time.perf_counter() - t0)
    tps = mb * gas * seq * steps / best
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(
        engine.state.params))
    flops = (6.0 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq) \
        * tps / 1e12
    return {"mb": mb, "gas": gas, "remat": remat, "policy": policy,
            "grad_accum_dtype": gad, "loss_chunk": loss_chunk,
            "tokens_per_sec": round(tps, 1), "tflops": round(flops, 1),
            "n_params": int(n_params)}


def main():
    if "--one" in sys.argv:
        i = sys.argv.index("--one")
        mb, gas, remat, policy, gad, lc = (
            int(sys.argv[i + 1]), int(sys.argv[i + 2]),
            int(sys.argv[i + 3]), sys.argv[i + 4], sys.argv[i + 5],
            int(sys.argv[i + 6]))
        try:
            print(TAG + json.dumps(run_one(mb, gas, remat, policy, gad, lc)))
        except Exception as e:
            print(TAG + json.dumps({"mb": mb, "gas": gas, "remat": remat,
                                    "gad": gad, "loss_chunk": lc,
                                    "error": f"{type(e).__name__}: {e}"[:200]}))
        return

    cands = [
        (2, 8, 0, "-", "bf16", 0),
        (2, 8, 0, "-", "bf16", 512),
        (4, 4, 1, "save_attn", "bf16", 512),
        (4, 4, 0, "-", "bf16", 512),
        (8, 2, 1, "save_attn", "bf16", 512),
    ]
    results = []
    for mb, gas, remat, policy, gad, lc in cands:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", str(mb),
             str(gas), str(remat), policy, gad, str(lc)],
            capture_output=True, text=True, timeout=1200)
        for line in p.stdout.splitlines():
            if line.startswith(TAG):
                r = json.loads(line[len(TAG):])
                results.append(r)
                print(r, flush=True)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
