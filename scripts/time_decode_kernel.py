"""Standalone per-call timing of fused_decode_step at 125M B=8 shapes
(chained-scan differencing: dispatch constant cancels)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.decode_step import fused_decode_step
from deepspeed_tpu.ops.attention import write_kv_cache, decode_attention

B, L, H, S, DH = 8, 12, 12, 640, 64
IDX = 543


def chain(n, fused=True):
    pair = 128 // DH
    rng = np.random.RandomState(0)
    if fused:
        kf = jnp.asarray(rng.randn(L, B, H, S // pair, DH * pair), jnp.bfloat16)
        vf = jnp.asarray(rng.randn(L, B, H, S // pair, DH * pair), jnp.bfloat16)
    else:
        kf = jnp.asarray(rng.randn(L, B, H, S, DH), jnp.bfloat16)
        vf = jnp.asarray(rng.randn(L, B, H, S, DH), jnp.bfloat16)
    q0 = jnp.asarray(rng.randn(B, 1, H, DH), jnp.bfloat16)

    @jax.jit
    def run(q, kf, vf):
        def step(carry, i):
            q, kf, vf = carry
            layer = jax.lax.rem(i, L)
            if fused:
                attn, kf, vf = fused_decode_step(
                    q, kf, vf, q, q, layer, jnp.int32(IDX))
            else:
                kf, vf, kl, vl = write_kv_cache(kf, vf, q, q, layer,
                                                jnp.int32(IDX))
                attn = decode_attention(q, kl, vl, jnp.int32(IDX))
            # feed attn back so steps serialize
            return (attn, kf, vf), None

        (q, kf, vf), _ = jax.lax.scan(step, (q, kf, vf),
                                      jnp.arange(n, dtype=jnp.int32))
        return q.astype(jnp.float32).sum()

    float(jax.device_get(run(q0, kf, vf)))
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        float(jax.device_get(run(q0, kf, vf)))
        best = min(best, time.perf_counter() - t0)
    return best


for name, fused in (("fused", True), ("einsum", False)):
    t1, t2 = chain(24, fused), chain(144, fused)
    per = (t2 - t1) / 120
    print(f"{name}: {per*1e6:.1f} us/call  ({per*12*1e3:.3f} ms per 12-layer step)")
