"""Dogfood the autotuner on the GPT-2-125M bench config (8-device mesh).

Compile-time search over the template knobs that matter for the bench
(micro-batch x gas x remat at ZeRO-2); the chosen config and every trial's
memory/roofline verdict are committed as AUTOTUNE_125M.json. Runs on the
virtual CPU mesh (self-bootstrapping subprocess, like scripts/memplan.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def run():
    import jax.numpy as jnp

    from deepspeed_tpu.autotuning import Autotuner
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    model = GPT2Model(GPT2Config.gpt2_125m(), compute_dtype=jnp.bfloat16)
    tuner = Autotuner(model, {
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "gradient_clipping": 1.0,
    }, seq_len=1024, vocab_size=50257, hbm_bytes=16e9,
        peak_flops=197e12, hbm_bw=819e9)
    best = tuner.tune(zero_stages=(2,), space={
        "micro_batch": [4, 8], "gas": [16],
        "offload": [False], "remat": [None, "dots_no_batch"]})
    out = {
        "best": best,
        "model_info": tuner.model_info(),
        "trials": [dataclasses.asdict(r) for r in tuner.results],
    }
    print("AUTOTUNE_JSON " + json.dumps(out))


def main():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["DSTPU_ACCELERATOR"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    code = (f"import sys; sys.path.insert(0, {_REPO!r}); "
            f"from scripts.autotune_125m import run; run()")
    proc = subprocess.run([sys.executable, "-c", code], cwd=_REPO, env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, timeout=3000)
    if proc.returncode != 0:
        sys.stdout.write(proc.stdout)
        raise SystemExit(f"autotune child failed rc={proc.returncode}")
    line = next(l for l in proc.stdout.splitlines()
                if l.startswith("AUTOTUNE_JSON "))
    out = json.loads(line[len("AUTOTUNE_JSON "):])
    with open(os.path.join(_REPO, "AUTOTUNE_125M.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out["best"], indent=1))
    print("wrote AUTOTUNE_125M.json")


if __name__ == "__main__":
    main()
