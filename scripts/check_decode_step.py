"""On-chip numerics check: fused_decode_step vs write_kv_cache + einsum."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.attention import decode_attention, write_kv_cache
from deepspeed_tpu.ops.decode_step import fused_decode_step


def check(b, l, hq, hkv, s, dh, idx_val):
    rng = np.random.RandomState(0)
    dt = jnp.bfloat16
    q = jnp.asarray(rng.randn(b, 1, hq, dh), dt)
    kf = jnp.asarray(rng.randn(l, b, hkv, s, dh), dt)
    vf = jnp.asarray(rng.randn(l, b, hkv, s, dh), dt)
    kn = jnp.asarray(rng.randn(b, 1, hkv, dh), dt)
    vn = jnp.asarray(rng.randn(b, 1, hkv, dh), dt)
    layer = jnp.int32(l // 2)
    idx = jnp.int32(idx_val)

    @jax.jit
    def ref(q, kf, vf, kn, vn):
        kf2, vf2, kl, vl = write_kv_cache(kf, vf, kn, vn, layer, idx)
        return decode_attention(q, kl, vl, idx), kf2, vf2

    @jax.jit
    def fused(q, kf, vf, kn, vn):
        return fused_decode_step(q, kf, vf, kn, vn, layer, idx)

    a0, k0, v0 = jax.device_get(ref(q, kf, vf, kn, vn))
    a1, k1, v1 = jax.device_get(fused(q, kf, vf, kn, vn))
    da = np.max(np.abs(a0.astype(np.float32) - a1.astype(np.float32)))
    dk = np.max(np.abs(k0.astype(np.float32) - k1.astype(np.float32)))
    dv = np.max(np.abs(v0.astype(np.float32) - v1.astype(np.float32)))
    print(f"b={b} l={l} hq={hq} hkv={hkv} s={s} dh={dh} idx={idx_val}: "
          f"attn_maxdiff={da:.5f} k={dk} v={dv}")
    assert da < 0.05, da
    assert dk == 0 and dv == 0


if __name__ == "__main__":
    print(jax.devices())
    check(8, 12, 12, 12, 640, 64, 543)       # 125M bench shape (MHA)
    check(1, 12, 12, 12, 640, 64, 0)         # first decode step, B=1
    check(8, 12, 12, 12, 640, 64, 639)       # last position
    check(2, 4, 32, 4, 640, 128, 300)        # GQA rep=8 (MXU path)
    check(1, 2, 16, 8, 256, 64, 100)         # GQA rep=2
    print("OK")
