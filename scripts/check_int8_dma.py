"""On-chip numerics + timing for the manual-DMA int8 matmul kernel."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.int8_matmul import _dma_plan, int8_matmul, int8_matmul_dma


def check(b, d, e):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, d), jnp.bfloat16)
    q = jnp.asarray(rng.randint(-127, 128, size=(d, e)), jnp.int8)
    s = jnp.asarray(np.abs(rng.randn(1, e)) * 0.01, jnp.float32)
    ref = (jnp.einsum("bd,de->be", x, q.astype(jnp.bfloat16))
           * s).astype(jnp.bfloat16)
    out = int8_matmul_dma(x, q, s)
    diff = np.max(np.abs(np.asarray(out, np.float32) - np.asarray(ref, np.float32)))
    rel = diff / (np.abs(np.asarray(ref, np.float32)).max() + 1e-9)
    print(f"b={b} [{d}x{e}] plan={_dma_plan(d, e)}: reldiff={rel:.4f}")
    assert rel < 0.02, rel


def timeit(b, d, e, fn, name, n1=16, n2=80):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, d), jnp.bfloat16)
    q = jnp.asarray(rng.randint(-127, 128, size=(d, e)), jnp.int8)
    s = jnp.asarray(np.abs(rng.randn(1, e)) * 0.01, jnp.float32)

    def chain(n):
        @jax.jit
        def f(x, q, s):
            acc = jnp.zeros((), jnp.float32)
            y = x
            for i in range(n):
                o = fn(y, q, s)
                t = o.astype(jnp.float32).sum()
                acc += t
                # scalar data dependency serializes the chain regardless
                # of output shape (XLA cannot collapse identical calls)
                y = x + (t * 1e-30).astype(x.dtype)
            return acc

        float(jax.device_get(f(x, q, s)))
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            float(jax.device_get(f(x, q, s)))
            best = min(best, time.perf_counter() - t0)
        return best

    per = (chain(n2) - chain(n1)) / (n2 - n1)
    gbs = d * e / per / 1e9
    print(f"{name} b={b} [{d}x{e}]: {per*1e6:.1f} us  ({gbs:.0f} GB/s weight stream)")
    return per


if __name__ == "__main__":
    print(jax.devices())
    check(1, 768, 2304)       # 125M qkv
    check(8, 768, 3072)       # 125M mlp
    check(1, 4096, 12288)     # 7B qkv
    check(1, 4096, 11008)     # llama mlp up (divisor-hostile)
    check(1, 11008, 4096)     # llama mlp down
    print("-- timing (differenced chains) --")
    for shape in ((768, 2304), (4096, 11008), (11008, 4096), (4096, 12288)):
        timeit(1, shape[0], shape[1], int8_matmul_dma, "dma", )
    # old gridded kernel at the 125M 1-cell shape for comparison
    timeit(1, 768, 2304, int8_matmul, "grid")
