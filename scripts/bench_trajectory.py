#!/usr/bin/env python
"""Collate per-round bench JSONs into a per-metric trend table.

Usage:
    python scripts/bench_trajectory.py                # BENCH_r*.json in repo root
    python scripts/bench_trajectory.py --full out.json  # + a fresh full bench JSON
    python scripts/bench_trajectory.py --json --threshold 0.15

The repo accumulates one ``BENCH_r<NN>.json`` per review round (shape:
``{"n": <round>, "parsed": {...bench.py main JSON...}}``) plus ad-hoc
full bench outputs — but until now nothing read them back, so the bench
trajectory was flying blind (ISSUE 11 satellite). This script flattens
every numeric leaf of each round's ``parsed`` payload into a dotted
metric path (``serving.bf16.decode_ms_per_token``), lines the rounds up
into per-metric series, and flags the newest value against the previous
round with a NOISE THRESHOLD (default 10% relative — the bench chip is
time-shared and identical configs swing between minutes; see bench.py's
best-of-windows commentary):

  * ``regression``  — moved past the threshold in the BAD direction
  * ``improvement`` — moved past the threshold in the GOOD direction
  * ``stable``      — within the threshold
  * ``new``/``gone`` — metric appeared/disappeared this round

Direction sense is a suffix heuristic: metrics named like latencies
(``*_ms``, ``*_ms_per_token``, ``*latency*``, ``*p50/p95/p99*``,
``*overhead*``) are lower-is-better; throughputs/ratios/MFU are
higher-is-better. Stdlib only — runs anywhere; unit-tested against the
checked-in round files (tests/unit/telemetry/test_trajectory.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import OrderedDict

_LOWER_IS_BETTER = re.compile(
    r"(_ms($|_)|_ms\.|latency|p50|p95|p99|overhead|ms_per_token"
    r"|n_bad|error|recompile|shed|failed)")


def lower_is_better(metric: str) -> bool:
    return bool(_LOWER_IS_BETTER.search(metric))


def flatten(obj, prefix="", out=None):
    """Numeric leaves of a nested dict as {dotted.path: float} (bools
    and non-numeric strings are skipped — they are config echoes, not
    trends)."""
    if out is None:
        out = OrderedDict()
    if isinstance(obj, dict):
        for k, v in obj.items():
            flatten(v, prefix + str(k) + ".", out)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix[:-1]] = float(obj)
    return out


def load_rounds(paths, full=None):
    """[(round_label, flat_metrics)] ordered by round. Round files carry
    their index in ``n``; a ``--full`` bench JSON (bench.py stdout) is
    appended as the newest point."""
    rounds = []
    for p in paths:
        with open(p) as f:
            d = json.load(f)
        parsed = d.get("parsed") if isinstance(d, dict) else None
        if not isinstance(parsed, dict):
            continue
        rounds.append((int(d.get("n", len(rounds) + 1)),
                       os.path.basename(p), flatten(parsed)))
    rounds.sort(key=lambda r: r[0])
    out = [(f"r{n:02d}", flat) for n, _, flat in rounds]
    if full:
        with open(full) as f:
            d = json.load(f)
        if not isinstance(d, dict):
            raise ValueError(f"--full {full}: expected a JSON object")
        out.append(("full", flatten(d)))
    return out


def _measured_spread(metric, flat):
    """IQR-derived relative noise for a metric that reports a measured
    spread (ISSUE 12 variance discipline): benches that emit
    ``<base>.median`` + ``<base>.iqr`` window statistics carry their
    OWN noise estimate, so the regression gate for ``<base>.median``
    (and a bare ``<base>`` echoing it) widens to the measured IQR
    instead of relying on the fixed global threshold alone. A
    best-of-windows HEADLINE whose spread rides under a sibling key
    uses the ``<metric>_windows`` convention (bench.py's top-level
    ``value`` + ``value_windows.{median,iqr,n}``). Returns None when
    the round carries no spread for this metric."""
    if metric.endswith(".median"):
        base = metric[:-len(".median")]
    else:
        base = metric
    for spread_base in (base, base + "_windows"):
        iqr = flat.get(spread_base + ".iqr")
        med = flat.get(spread_base + ".median", flat.get(metric))
        if iqr is not None and med:
            return abs(iqr) / abs(med)
    return None


def trend(rounds, threshold=0.10):
    """Per-metric series + newest-vs-previous flag. Returns
    {metric: {"series": {label: value}, "flag": ..., "delta_pct": ...}}
    over the union of metrics, sorted by path. Metrics whose last path
    component is ``iqr``/``n`` are spread METADATA, flagged ``spread``
    and never counted as regressions; a metric accompanied by a
    measured spread is gated at ``max(threshold, IQR/median)`` of the
    newer round — the bench's own window noise."""
    if not rounds:
        return {}
    labels = [lbl for lbl, _ in rounds]
    metrics = sorted({m for _, flat in rounds for m in flat})
    out = OrderedDict()
    last_lbl = labels[-1]
    last_flat = rounds[-1][1]
    for m in metrics:
        series = OrderedDict((lbl, flat[m]) for lbl, flat in rounds
                             if m in flat)
        rec = {"series": series}
        present = list(series)
        if m.rsplit(".", 1)[-1] in ("iqr", "n"):
            rec["flag"] = "spread"
        elif last_lbl not in series:
            rec["flag"] = "gone"
        elif len(present) == 1:
            rec["flag"] = "new"
        else:
            prev = series[present[-2]]
            cur = series[present[-1]]
            if prev == 0:
                rec["flag"] = "stable" if cur == 0 else "new_nonzero"
            else:
                delta = (cur - prev) / abs(prev)
                rec["delta_pct"] = round(delta * 100.0, 2)
                eff = threshold
                spread = _measured_spread(m, last_flat)
                if spread is not None:
                    eff = max(eff, spread)
                    rec["threshold_pct"] = round(eff * 100.0, 2)
                if abs(delta) <= eff:
                    rec["flag"] = "stable"
                else:
                    worse = delta > 0 if lower_is_better(m) else delta < 0
                    rec["flag"] = "regression" if worse else "improvement"
        out[m] = rec
    return out


def _trend_rows(t, only_flagged=False):
    rows = []
    for m, rec in t.items():
        if only_flagged and rec["flag"] in ("stable", "new", "gone",
                                            "spread"):
            continue
        series = rec["series"]
        vals = " ".join(f"{lbl}={v:g}" for lbl, v in series.items())
        delta = (f"{rec['delta_pct']:+.1f}%" if "delta_pct" in rec
                 else "-")
        rows.append((m, rec["flag"], delta, vals))
    return rows


def render(t, only_flagged=False):
    rows = _trend_rows(t, only_flagged)
    if not rows:
        return "bench trajectory: no metrics" + \
            (" flagged" if only_flagged else " found")
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              for i, h in enumerate(("metric", "flag", "delta", "series"))]
    lines = ["  ".join(h.ljust(w) for h, w in
                       zip(("metric", "flag", "delta", "series"), widths)),
             "  ".join("-" * w for w in widths)]
    for r in rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def render_markdown(t, rounds, only_flagged=False):
    """GitHub-flavored markdown trend report (ISSUE 13 satellite) —
    pasteable into a PR description or review round: one table row per
    metric, flags bolded so regressions jump out, and a summary line
    up top. ``|`` in metric paths (none today) would be escaped by the
    cell join; series cells use the same ``label=value`` form as the
    text renderer."""
    rows = _trend_rows(t, only_flagged)
    n_reg = sum(r["flag"] == "regression" for r in t.values())
    n_imp = sum(r["flag"] == "improvement" for r in t.values())
    lines = [
        f"## Bench trajectory",
        "",
        f"{len(rounds)} round(s) ({', '.join(lbl for lbl, _ in rounds)}), "
        f"{len(t)} metric(s): **{n_reg} regression(s)**, "
        f"{n_imp} improvement(s).",
        "",
    ]
    if not rows:
        lines.append("_no metrics" +
                     (" flagged_" if only_flagged else " found_"))
        return "\n".join(lines)
    lines.append("| metric | flag | delta | series |")
    lines.append("| --- | --- | --- | --- |")
    for m, flag, delta, vals in rows:
        shown = f"**{flag}**" if flag in ("regression", "improvement") \
            else flag
        cells = [str(c).replace("|", "\\|")
                 for c in (f"`{m}`", shown, delta, vals)]
        lines.append("| " + " | ".join(cells) + " |")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*",
                   help="round files (default: BENCH_r*.json in repo root)")
    p.add_argument("--full", default=None,
                   help="a full bench.py JSON output, appended as the "
                        "newest point")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="relative noise threshold (default 0.10 = 10%%)")
    p.add_argument("--flagged", action="store_true",
                   help="show only regressions/improvements")
    p.add_argument("--json", action="store_true",
                   help="emit the trend dict as JSON")
    p.add_argument("--markdown", action="store_true",
                   help="emit the trend table as GitHub-flavored "
                        "markdown (one row per metric, regression/"
                        "improvement flags bolded)")
    args = p.parse_args(argv)
    paths = args.paths
    if not paths:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    if not paths and not args.full:
        print("bench_trajectory: no BENCH_r*.json files found",
              file=sys.stderr)
        return 2
    rounds = load_rounds(paths, full=args.full)
    t = trend(rounds, threshold=args.threshold)
    if args.json:
        print(json.dumps({"threshold": args.threshold, "rounds":
                          [lbl for lbl, _ in rounds], "metrics": t},
                         indent=2))
    elif args.markdown:
        print(render_markdown(t, rounds, only_flagged=args.flagged))
    else:
        n_reg = sum(r["flag"] == "regression" for r in t.values())
        n_imp = sum(r["flag"] == "improvement" for r in t.values())
        print(f"bench trajectory — {len(rounds)} rounds, {len(t)} metrics, "
              f"{n_reg} regression(s), {n_imp} improvement(s) "
              f"@ {args.threshold:.0%} threshold\n")
        print(render(t, only_flagged=args.flagged))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
