"""GQA serving evidence on-chip (round-4 VERDICT #9): a 32q/4kv-head
config through the engine's decode, fused kernel vs einsum, dual-length
differenced (the bench.py methodology)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
from deepspeed_tpu.utils import groups

PROMPT, LONG, SHORT, TRIALS = 512, 128, 8, 7


def measure(batch, use_kernel):
    import deepspeed_tpu.ops.attention as att

    orig = None
    if not use_kernel:
        from deepspeed_tpu.ops import decode_step

        orig = decode_step.supports
        decode_step.supports = lambda *a, **k: False
    try:
        groups.reset()
        cfg = LlamaConfig(num_layers=8, hidden_size=4096, num_heads=32,
                          num_kv_heads=4, max_seq_len=1024)
        engine = deepspeed_tpu.init_inference(
            LlamaModel(cfg), dtype="bf16", max_out_tokens=PROMPT + LONG + 1)
        rs = np.random.RandomState(0)

        def fresh():
            return rs.randint(0, cfg.vocab_size,
                              size=(batch, PROMPT)).astype(np.int32)

        temp = jnp.float32(1.0)
        med = {}
        for mn in (SHORT, LONG):
            pf, dec = engine.compiled_programs(batch, PROMPT, mn)
            rng = jax.random.PRNGKey(0)
            tok, cache, rng = pf(engine.params, jnp.asarray(fresh()), temp, rng)
            _ = np.asarray(jax.device_get(dec(engine.params, tok, cache, temp, rng)))
            ts = []
            for i in range(TRIALS):
                rng = jax.random.PRNGKey(i)
                tok, cache, rng = pf(engine.params, jnp.asarray(fresh()),
                                     temp, rng)
                _ = np.asarray(jax.device_get(tok))
                t0 = time.perf_counter()
                out = dec(engine.params, tok, cache, temp, rng)
                _ = np.asarray(jax.device_get(out))
                ts.append(time.perf_counter() - t0)
            ts.sort()
            med[mn] = ts[len(ts) // 2]
        per = (med[LONG] - med[SHORT]) / (LONG - SHORT)
        del engine
        return per
    finally:
        if orig is not None:
            from deepspeed_tpu.ops import decode_step

            decode_step.supports = orig


if __name__ == "__main__":
    print(jax.devices())
    for b in (1, 8):
        k = measure(b, True)
        e = measure(b, False)
        print(f"GQA 32q/4kv dh=128 L=8 B={b}: fused {k*1e3:.3f} ms/tok vs "
              f"einsum {e*1e3:.3f} ms/tok ({e/k:.2f}x)", flush=True)
