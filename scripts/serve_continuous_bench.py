"""Continuous-batching serving bench (ISSUE 2 / ISSUE 4 acceptance
numbers only).

Default: bench.py's serving-comparison section standalone — aggregate
tokens/sec + p50/p95 per-request latency of the continuous-batching
runtime (deepspeed_tpu/serving) vs run-to-completion static batching at
the same slot count, under a mixed-length Poisson arrival trace.

``--speculative {off,ngram,draft}``: the ISSUE-4 comparison instead —
speculative decoding (prompt-lookup n-gram or draft-model drafting)
vs plain continuous batching on the same templated high-acceptance
trace, reporting decode tokens/sec, p50/p95 latency, acceptance rate,
tokens per verify invocation, and the zero-recompile check.

``--prefix-cache {on,off}``: the ISSUE-6 comparison instead — block-paged
KV with radix prefix sharing (on) vs the plain slot-paged engine (off is
the default continuous-vs-static bench) on a shared-prefix multi-tenant
trace, reporting TTFT p50/p95, prefill tokens computed, cache hit rate,
COW/eviction counters, and the zero-recompile + lossless checks.

``--slo``: the ISSUE-8 comparison instead — SLO-aware serving (chunked
prefill under a per-iteration token budget, priority classes with
aging, preemption with host KV swap) vs the FIFO monolithic-prefill
engine on a bimodal long-prompt trace, reporting decode-TPOT
(inter-token latency) and TTFT p50/p95/p99 overall and per priority
class, throughput, preemption/chunk counters, and the zero-recompile +
lossless checks in BOTH cache modes.

Usage: python scripts/serve_continuous_bench.py [--speculative MODE]
                                                [--prefix-cache {on,off}]
                                                [--slo]
Prints one JSON object (the matching entry of bench.py).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--speculative", choices=("off", "ngram", "draft"),
                   default="off",
                   help="compare speculative decoding (n-gram prompt-"
                        "lookup or draft-model drafting) against plain "
                        "continuous batching instead of continuous-vs-"
                        "static")
    p.add_argument("--prefix-cache", choices=("on", "off"), default="off",
                   help="compare the block-paged radix prefix cache "
                        "against the cache-off engine on a shared-prefix "
                        "multi-tenant trace instead of continuous-vs-"
                        "static")
    p.add_argument("--slo", action="store_true",
                   help="compare SLO-aware serving (chunked prefill + "
                        "priority classes + preemption w/ host KV swap) "
                        "against the FIFO monolithic-prefill engine on a "
                        "bimodal long-prompt trace, both cache modes, "
                        "instead of continuous-vs-static")
    args = p.parse_args()
    exclusive = [args.prefix_cache == "on", args.speculative != "off",
                 args.slo]
    if sum(exclusive) > 1:
        p.error("--prefix-cache on, --speculative, and --slo are separate "
                "comparisons; pass one of them")

    import jax

    from bench import (_bench_continuous_serving,
                       _bench_prefix_cache_serving,
                       _bench_slo_serving,
                       _bench_speculative_serving)

    on_tpu = any(d.platform in ("tpu", "axon") or "TPU" in str(d.device_kind)
                 for d in jax.devices())
    if args.slo:
        out = _bench_slo_serving(on_tpu)
    elif args.prefix_cache == "on":
        out = _bench_prefix_cache_serving(on_tpu)
    elif args.speculative != "off":
        out = _bench_speculative_serving(on_tpu, mode=args.speculative)
    else:
        out = _bench_continuous_serving(on_tpu)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
