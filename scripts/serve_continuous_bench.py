"""Continuous-batching serving bench (ISSUE 2 acceptance numbers only).

Runs bench.py's serving-comparison section standalone: aggregate
tokens/sec + p50/p95 per-request latency of the continuous-batching
runtime (deepspeed_tpu/serving) vs run-to-completion static batching at
the same slot count, under a mixed-length Poisson arrival trace.

Usage: python scripts/serve_continuous_bench.py
Prints one JSON object (the "serving_continuous" entry of bench.py).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    from bench import _bench_continuous_serving

    on_tpu = any(d.platform in ("tpu", "axon") or "TPU" in str(d.device_kind)
                 for d in jax.devices())
    print(json.dumps(_bench_continuous_serving(on_tpu), indent=2))


if __name__ == "__main__":
    main()
