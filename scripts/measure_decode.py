"""In-engine decode measurement (PROFILE_DECODE dual-length differencing).

Usage: python scripts/measure_decode.py [bf16|int8] [batches...]
Prints per-config ms/tok + the decode program's KV carry layout.
"""
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.utils import groups

PROMPT = 512
LONG, SHORT = 128, 8
TRIALS = 7


def measure(dtype, batch, cfg=None):
    groups.reset()
    cfg = cfg or GPT2Config.gpt2_125m()
    rs = np.random.RandomState(0)

    def fresh():
        return rs.randint(0, cfg.vocab_size, size=(batch, PROMPT)).astype(np.int32)

    engine = deepspeed_tpu.init_inference(
        GPT2Model(cfg), dtype=dtype, max_out_tokens=PROMPT + LONG + 1)
    temp = jnp.float32(1.0)
    med = {}
    for mn in (SHORT, LONG):
        pf, dec = engine.compiled_programs(batch, PROMPT, mn)
        # warm compile
        rng = jax.random.PRNGKey(0)
        tok, cache, rng = pf(engine.params, jnp.asarray(fresh()), temp, rng)
        _ = np.asarray(jax.device_get(dec(engine.params, tok, cache, temp, rng)))
        ts = []
        for i in range(TRIALS):
            rng = jax.random.PRNGKey(i)
            tok, cache, rng = pf(engine.params, jnp.asarray(fresh()), temp, rng)
            _ = np.asarray(jax.device_get(tok))
            t0 = time.perf_counter()
            toks = dec(engine.params, tok, cache, temp, rng)
            _ = np.asarray(jax.device_get(toks))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        med[mn] = ts[len(ts) // 2]
    per_tok = (med[LONG] - med[SHORT]) / (LONG - SHORT)
    print(f"dtype={dtype} B={batch}: {per_tok*1e3:.3f} ms/tok "
          f"({batch/per_tok:.0f} tok/s aggregate)  "
          f"[med_short={med[SHORT]*1e3:.1f}ms med_long={med[LONG]*1e3:.1f}ms]")
    del engine
    return per_tok


def measure_b1_dh128():
    """ADVICE round 5: the fused decode kernel's fixed per-layer DMA cost
    was never measured at B=1 with Dh>=128 (LLaMA geometry — Dh=128
    never packs, so the allocation-shape gate that keeps 125M B=1 on the
    einsum does not apply). Run a mid-size Dh=128 model at B=1 with the
    kernel forced ON and forced OFF via the byte-threshold env override
    (ops/attention._B1_FUSED_MIN_BYTES) and print both ms/tok; set the
    threshold between the two geometries' per-layer stream bytes if the
    einsum wins."""
    import subprocess

    env_on = dict(os.environ, DEEPSPEED_TPU_B1_FUSED_MIN_BYTES="0")
    env_off = dict(os.environ,
                   DEEPSPEED_TPU_B1_FUSED_MIN_BYTES=str(1 << 40))
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from scripts.measure_decode import measure\n"
        "from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel\n"
        "import deepspeed_tpu, jax.numpy as jnp, numpy as np, jax, time\n"
        "from deepspeed_tpu.utils import groups\n"
        "cfg = LlamaConfig(num_layers=12, hidden_size=1024, num_heads=8,\n"
        "                  num_kv_heads=8, vocab_size=32000,\n"
        "                  max_seq_len=2048)\n"
        "assert cfg.head_dim == 128\n"
        "groups.reset()\n"
        "rs = np.random.RandomState(0)\n"
        "eng = deepspeed_tpu.init_inference(LlamaModel(cfg), dtype='bf16',\n"
        "    max_out_tokens=512 + 129)\n"
        "temp = jnp.float32(1.0)\n"
        "med = {}\n"
        "for mn in (8, 128):\n"
        "    pf, dec = eng.compiled_programs(1, 512, mn)\n"
        "    rng = jax.random.PRNGKey(0)\n"
        "    ids = jnp.asarray(rs.randint(0, 32000, size=(1, 512),\n"
        "                      dtype=np.int32))\n"
        "    tok, cache, rng = pf(eng.params, ids, temp, rng)\n"
        "    _ = np.asarray(jax.device_get(dec(eng.params, tok, cache,\n"
        "                                      temp, rng)))\n"
        "    ts = []\n"
        "    for i in range(7):\n"
        "        rng = jax.random.PRNGKey(i)\n"
        "        tok, cache, rng = pf(eng.params, ids, temp, rng)\n"
        "        _ = np.asarray(jax.device_get(tok))\n"
        "        t0 = time.perf_counter()\n"
        "        _ = np.asarray(jax.device_get(dec(eng.params, tok, cache,\n"
        "                                          temp, rng)))\n"
        "        ts.append(time.perf_counter() - t0)\n"
        "    ts.sort(); med[mn] = ts[len(ts) // 2]\n"
        "print('PER_TOK_MS=%%.4f' %% ((med[128] - med[8]) / 120 * 1e3))\n"
    ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name, env in (("fused", env_on), ("einsum", env_off)):
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=1800)
        line = [ln for ln in p.stdout.splitlines()
                if ln.startswith("PER_TOK_MS=")]
        print(f"B=1 Dh=128 (LLaMA-geometry 12L/1024d) {name}: "
              f"{line[0].split('=')[1] if line else 'FAILED'} ms/tok"
              + ("" if line else f"\n{p.stderr[-500:]}"))


if __name__ == "__main__":
    if "--b1-dh128" in sys.argv:
        measure_b1_dh128()
        sys.exit(0)
    dtypes = [sys.argv[1]] if len(sys.argv) > 1 else ["bf16"]
    batches = [int(a) for a in sys.argv[2:]] or [1, 8]
    res = {}
    for dt in dtypes:
        for b in batches:
            res[(dt, b)] = measure(dt, b)
    if ("bf16", 1) in res and ("bf16", 8) in res:
        r = 8 * res[("bf16", 1)] / res[("bf16", 8)]
        print(f"bf16 batch8/batch1 aggregate ratio: {r:.2f}x")
