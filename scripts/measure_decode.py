"""In-engine decode measurement (PROFILE_DECODE dual-length differencing).

Usage: python scripts/measure_decode.py [bf16|int8] [batches...]
Prints per-config ms/tok + the decode program's KV carry layout.
"""
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.utils import groups

PROMPT = 512
LONG, SHORT = 128, 8
TRIALS = 7


def measure(dtype, batch, cfg=None):
    groups.reset()
    cfg = cfg or GPT2Config.gpt2_125m()
    rs = np.random.RandomState(0)

    def fresh():
        return rs.randint(0, cfg.vocab_size, size=(batch, PROMPT)).astype(np.int32)

    engine = deepspeed_tpu.init_inference(
        GPT2Model(cfg), dtype=dtype, max_out_tokens=PROMPT + LONG + 1)
    temp = jnp.float32(1.0)
    med = {}
    for mn in (SHORT, LONG):
        pf, dec = engine.compiled_programs(batch, PROMPT, mn)
        # warm compile
        rng = jax.random.PRNGKey(0)
        tok, cache, rng = pf(engine.params, jnp.asarray(fresh()), temp, rng)
        _ = np.asarray(jax.device_get(dec(engine.params, tok, cache, temp, rng)))
        ts = []
        for i in range(TRIALS):
            rng = jax.random.PRNGKey(i)
            tok, cache, rng = pf(engine.params, jnp.asarray(fresh()), temp, rng)
            _ = np.asarray(jax.device_get(tok))
            t0 = time.perf_counter()
            toks = dec(engine.params, tok, cache, temp, rng)
            _ = np.asarray(jax.device_get(toks))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        med[mn] = ts[len(ts) // 2]
    per_tok = (med[LONG] - med[SHORT]) / (LONG - SHORT)
    print(f"dtype={dtype} B={batch}: {per_tok*1e3:.3f} ms/tok "
          f"({batch/per_tok:.0f} tok/s aggregate)  "
          f"[med_short={med[SHORT]*1e3:.1f}ms med_long={med[LONG]*1e3:.1f}ms]")
    del engine
    return per_tok


if __name__ == "__main__":
    dtypes = [sys.argv[1]] if len(sys.argv) > 1 else ["bf16"]
    batches = [int(a) for a in sys.argv[2:]] or [1, 8]
    res = {}
    for dt in dtypes:
        for b in batches:
            res[(dt, b)] = measure(dt, b)
    if ("bf16", 1) in res and ("bf16", 8) in res:
        r = 8 * res[("bf16", 1)] / res[("bf16", 8)]
        print(f"bf16 batch8/batch1 aggregate ratio: {r:.2f}x")
