#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, verbatim. Run from the repo root.
# The `-m 'not slow'` selection includes the quick continuous-batching
# serving tests (tests/unit/serving, marker `serving`), so tier-1
# exercises the scheduler/kv-slot/no-recompile path; the explicit check
# afterwards fails the script if that suite was ever emptied out.
# conftest.py prints a "module wall-clock (child subprocess)" section at
# the end of the run — the per-module duration summary that shows where
# the 870s budget goes when deciding which modules to demote to `slow`.
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# the serving suite must exist and be non-empty (it rides the
# `-m 'not slow'` selection above; a second pytest invocation here was
# flaky under post-suite memory pressure, so guard on the files)
grep -rqs "def test_" tests/unit/serving || { echo "tier-1: serving tests missing"; exit 1; }
# likewise the observability suite (marker `observability`): the telemetry
# registry/sink + engine/serving instrumentation tests ride `-m 'not slow'`
grep -rqs "def test_" tests/unit/telemetry || { echo "tier-1: observability tests missing"; exit 1; }
# likewise the speculative-decoding suite (marker `speculative`): the
# lossless-greedy/rejection-sampling/zero-recompile invariants ride
# `-m 'not slow'` through tests/unit/serving/test_speculative.py
grep -qs "def test_" tests/unit/serving/test_speculative.py || { echo "tier-1: speculative tests missing"; exit 1; }
# likewise the prefix-cache suite (marker `prefix_cache`): block-paged
# KV + radix COW-losslessness/eviction/zero-recompile invariants ride
# `-m 'not slow'` through tests/unit/serving/test_prefix_cache.py
grep -qs "def test_" tests/unit/serving/test_prefix_cache.py || { echo "tier-1: prefix-cache tests missing"; exit 1; }
# likewise the SLO-scheduling suite (marker `slo`): chunked-prefill
# losslessness, priority/preemption KV-swap round-trip bit-identity and
# zero-recompile invariants ride `-m 'not slow'` through
# tests/unit/serving/test_slo.py
grep -qs "def test_" tests/unit/serving/test_slo.py || { echo "tier-1: slo tests missing"; exit 1; }
# likewise the serving-fabric suite (marker `fabric`): multi-replica
# failover losslessness under scripted chaos, circuit-breaker /
# shedding / supervisor invariants ride `-m 'not slow'` through
# tests/unit/serving/test_fabric.py
grep -qs "def test_" tests/unit/serving/test_fabric.py || { echo "tier-1: fabric tests missing"; exit 1; }
# likewise the training-resilience suite (marker `resilience`): anomaly
# classification, finite-grad guard, rewind-and-skip bit-identity,
# deterministic dataloader resume and SDC-audit invariants ride
# `-m 'not slow'` through tests/unit/runtime/test_resilience.py
grep -qs "def test_" tests/unit/runtime/test_resilience.py || { echo "tier-1: resilience tests missing"; exit 1; }
# likewise the tracing suite (marker `tracing`): span-graph lifecycle
# reconstruction incl. failover trace linking, armed-run greedy
# bit-identity, Chrome-trace validity and roofline attribution ride
# `-m 'not slow'` through tests/unit/serving/test_tracing.py and
# tests/unit/telemetry/test_spans.py
grep -qs "def test_" tests/unit/serving/test_tracing.py || { echo "tier-1: tracing tests missing"; exit 1; }
grep -qs "def test_" tests/unit/telemetry/test_spans.py || { echo "tier-1: span tests missing"; exit 1; }
# likewise the quantized-KV suite (marker `kvquant`): int8/fp8 block
# round-trip bounds, capacity ratios, fused dequant-kernel parity,
# greedy exact-match gate, COW/swap/prefix-hit invariants on quantized
# pools, and autotuned kernel-plan loading ride `-m 'not slow'` through
# tests/unit/serving/test_kv_quant.py
grep -qs "def test_" tests/unit/serving/test_kv_quant.py || { echo "tier-1: kv-quant tests missing"; exit 1; }
# likewise the SLO control-plane suite (marker `sloplane`): burn-rate
# window math + multi-window alert determinism, per-tenant accounting
# conservation, flight-recorder dump/postmortem reconstruction and
# report degrade paths ride `-m 'not slow'` through
# tests/unit/telemetry/test_slo_plane.py and
# tests/unit/serving/test_slo_plane.py
grep -qs "def test_" tests/unit/telemetry/test_slo_plane.py || { echo "tier-1: slo-plane tests missing"; exit 1; }
grep -qs "def test_" tests/unit/serving/test_slo_plane.py || { echo "tier-1: slo-plane serving tests missing"; exit 1; }
# likewise the static-analysis suite (marker `lint`): each dstpu-lint
# pass catches its seeded fixture violation and stays silent on the
# good twin, suppression/baseline round-trips, and the repo-clean
# end-to-end pin ride `-m 'not slow'` through tests/unit/analysis/
grep -qs "def test_" tests/unit/analysis/test_lint.py || { echo "tier-1: lint tests missing"; exit 1; }
# dstpu-lint (ISSUE 14; prove upgrade ISSUE 15): machine-enforce the
# static contracts — zero unsuppressed findings across host-sync (a
# reintroduced hot-path device_get fails here), recompile-hazard
# (unbucketed jit keys), typed-error (bare raises in serving/),
# jax-compat (direct version-gated imports), donation-safety,
# metric-names, slo-rules, plus the ISSUE 15 TPU-native families:
# pallas-tile (dtype tile quanta — an int8 window off the 32-row
# quantum fails here), pallas-dma (a dropped DMA .wait() fails here),
# vmem-budget (committed kernel plans must fit the ops/autotune.py
# VMEM table), and sharding-contract (interprocedural donation taint +
# the mesh-axis registry). Exit codes: 1 findings / 2 usage /
# 3 internal. Incremental mode first (per-file finding cache keyed on
# content hashes — byte-identical output to a full run, pinned by
# test); full-corpus fallback on usage/internal errors so a corrupt
# cache or missing git can never mask findings. LINT_BASELINE.json's
# committed budget stays the growth guard: the baseline only burns
# down. Wall-clock stays under 60 s (pinned by
# tests/unit/analysis/test_prove.py).
JAX_PLATFORMS=cpu python scripts/dstpu_lint.py --changed-only; lint_rc=$?
if [ "$lint_rc" -eq 2 ] || [ "$lint_rc" -eq 3 ]; then
  echo "tier-1: incremental lint unavailable (rc=$lint_rc), full run"
  JAX_PLATFORMS=cpu python scripts/dstpu_lint.py; lint_rc=$?
fi
[ "$lint_rc" -eq 0 ] || { echo "tier-1: dstpu-lint findings"; exit 1; }
# bench-trajectory smoke (ISSUE 13 satellite): the markdown trend
# report must render over the checked-in BENCH_r*.json round files
python scripts/bench_trajectory.py --markdown > /dev/null || { echo "tier-1: bench trajectory markdown"; exit 1; }
exit $rc
