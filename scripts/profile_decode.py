"""Decode-latency profiling on the real chip: batch-1 and batch-8 decode
ms/token via the bench.py shape-differencing methodology (tunnel RTT and
prefill cost cancel), across decode_unroll settings.

Usage: python scripts/profile_decode.py [--quick]
"""
import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model  # noqa: E402


def timed(engine, ids, n_new, trials):
    engine.generate(ids, max_new_tokens=n_new)  # compile
    ts = []
    for _ in range(trials):
        t0 = time.perf_counter()
        engine.generate(ids, max_new_tokens=n_new)
        ts.append(time.perf_counter() - t0)
    return sorted(ts)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--unrolls", default="1,2,4,12")
    ap.add_argument("--batches", default="1,8")
    ap.add_argument("--dtype", default="bf16")
    args = ap.parse_args()

    prompt_len, decode_len, trials = (64, 8, 3) if args.quick else (512, 64, 9)
    cfg = GPT2Config.gpt2_125m()
    rng = np.random.RandomState(0)
    results = {}
    for unroll in [int(u) for u in args.unrolls.split(",")]:
        for b in [int(x) for x in args.batches.split(",")]:
            ids = rng.randint(0, cfg.vocab_size, size=(b, prompt_len)).astype(np.int32)
            engine = deepspeed_tpu.init_inference(
                GPT2Model(cfg, decode_unroll=unroll), dtype=args.dtype,
                max_out_tokens=prompt_len + decode_len + 1)
            pre = timed(engine, ids, 1, trials)
            full = timed(engine, ids, decode_len + 1, trials)
            dec = full[0] - pre[0]
            # time-shared chip: a noisy window can make the difference
            # non-positive — report the sample as invalid, never negative
            results[f"unroll{unroll}_b{b}"] = {
                "decode_ms_per_token": round(dec * 1e3 / decode_len, 3) if dec > 0 else None,
                "agg_tokens_per_sec": round(b * decode_len / dec, 1) if dec > 0 else None,
                "prefill_best_ms": round(pre[0] * 1e3, 2),
            }
            print(f"unroll={unroll} b={b}: {results[f'unroll{unroll}_b{b}']}",
                  flush=True)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
