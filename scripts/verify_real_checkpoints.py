"""Real-checkpoint parity verification (driver-runnable).

This image is zero-egress and ships no cached checkpoints, so round-to-round
CI proves weight-mapping parity against RANDOM-INIT HF models
(tests/unit/inference/test_policies.py). This script closes the remaining
gap the moment it runs anywhere with network or a populated HF cache:

  1. GPT-2 (124M real weights): HF torch logits vs this framework's
     converted serving engine — asserts allclose.
  2. LLaMA-class (any causal LM id passed via --llama): same check.
  3. Stable Diffusion (needs `diffusers`): UNet/VAE/CLIP converted via
     inference/policies + models/diffusion; asserts DDIM latents parity.

Usage:
    python scripts/verify_real_checkpoints.py [--gpt2 gpt2]
        [--llama meta-llama/Llama-2-7b-hf] [--sd runwayml/stable-diffusion-v1-5]

Exit 0 = every check that could run passed; checks whose weights/libs are
unavailable are reported as SKIPPED (exit stays 0 unless a runnable check
fails). Results land in CHECKPOINT_PARITY.json at the repo root.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

RESULTS = {}


def _record(name, status, detail=""):
    RESULTS[name] = {"status": status, "detail": detail}
    print(f"[{status}] {name}: {detail}")


@functools.lru_cache(maxsize=1)
def _hf_cache_dirs():
    """Every place weights could already live on this machine: HF env-var
    caches, the default hub cache, and vendored-weights directories."""
    dirs = []
    for env in ("HF_HOME", "TRANSFORMERS_CACHE", "HF_HUB_CACHE"):
        v = os.environ.get(env)
        if v:
            dirs += [v, os.path.join(v, "hub")]
    dirs += [os.path.expanduser("~/.cache/huggingface/hub"),
             "/root/weights", "/opt/weights", os.path.join(_REPO, "weights")]
    return [d for d in dict.fromkeys(dirs) if os.path.isdir(d)]


@functools.lru_cache(maxsize=1)
def _discover_local_snapshots():
    """(model_name, path) for every locally cached (hub layout) or vendored
    (flat directory with config.json) HF model — probed BEFORE declaring
    any check SKIPPED, so a populated cache is used even offline."""
    found = []
    for root in _hf_cache_dirs():
        for entry in sorted(os.listdir(root)):
            p = os.path.join(root, entry)
            if entry.startswith("models--") and os.path.isdir(
                    os.path.join(p, "snapshots")):
                snaps = os.path.join(p, "snapshots")
                for rev in sorted(os.listdir(snaps)):
                    sp = os.path.join(snaps, rev)
                    if os.path.exists(os.path.join(sp, "config.json")):
                        found.append(
                            (entry[len("models--"):].replace("--", "/"), sp))
                        break
            elif os.path.isdir(p) and os.path.exists(
                    os.path.join(p, "config.json")):
                found.append((entry, p))
    return found


def _load_hf(model_id: str, cls):
    """Try the local cache/vendored snapshots first, then the network."""
    try:
        return cls.from_pretrained(model_id, local_files_only=True), "local"
    except Exception:
        pass
    for name, path in _discover_local_snapshots():
        if name == model_id or name.endswith("/" + model_id):
            try:
                return cls.from_pretrained(path), f"vendored:{path}"
            except Exception:
                continue
    return cls.from_pretrained(model_id), "network"


def check_causal_lm(model_id: str, name: str, prompt_len: int = 16):
    try:
        import torch
        import transformers
    except ImportError as e:
        return _record(name, "SKIPPED", f"missing lib: {e}")
    try:
        hf, source = _load_hf(model_id, transformers.AutoModelForCausalLM)
        print(f"  ({name}: weights from {source})")
    except Exception as e:
        return _record(
            name, "SKIPPED",
            f"weights unavailable locally ({len(_hf_cache_dirs())} cache "
            f"dirs probed) and no network: {e}")
    hf = hf.eval()
    import deepspeed_tpu

    vocab = hf.config.vocab_size
    ids = np.random.RandomState(0).randint(0, vocab, (2, prompt_len))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.float().numpy()
    engine = deepspeed_tpu.init_inference(hf, dtype="fp32")
    ours = np.asarray(engine.forward(ids.astype(np.int32))).astype(np.float32)
    err = float(np.max(np.abs(ours - ref)))
    try:
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)
    except AssertionError:
        return _record(name, "FAILED", f"max abs logit err {err:.4f}")
    # greedy rollouts must also agree token-for-token
    our_toks = engine.generate(ids[:1].astype(np.int32), max_new_tokens=8)
    with torch.no_grad():
        # min_new_tokens keeps HF from stopping at EOS early — our side is
        # not passed an eos_token_id, so the arrays must be length-equal
        hf_toks = hf.generate(torch.tensor(ids[:1]), max_new_tokens=8,
                              min_new_tokens=8, do_sample=False).numpy()
    if not np.array_equal(our_toks, hf_toks):
        return _record(name, "FAILED",
                       f"greedy rollouts diverge: {our_toks} vs {hf_toks}")
    _record(name, "PASSED", f"max abs logit err {err:.5f}; greedy rollout equal")


def check_stable_diffusion(model_id: str):
    name = f"sd:{model_id}"
    try:
        import diffusers  # noqa: F401
        import torch
    except ImportError as e:
        return _record(name, "SKIPPED", f"missing lib: {e}")
    try:
        from diffusers import StableDiffusionPipeline

        pipe, source = _load_hf(model_id, StableDiffusionPipeline)
        print(f"  ({name}: weights from {source})")
    except Exception as e:
        return _record(name, "SKIPPED", f"weights unavailable: {e}")
    import jax.numpy as jnp

    from deepspeed_tpu.inference.diffusion import convert_diffusers_unet
    from deepspeed_tpu.models.diffusion import UNet2DConditionModel, UNetConfig

    hc = pipe.unet.config
    cfg = UNetConfig(
        in_channels=hc.in_channels, out_channels=hc.out_channels,
        block_out_channels=tuple(hc.block_out_channels),
        layers_per_block=hc.layers_per_block,
        down_block_types=tuple(hc.down_block_types),
        up_block_types=tuple(hc.up_block_types),
        cross_attention_dim=hc.cross_attention_dim,
        attention_head_dim=hc.attention_head_dim
        if isinstance(hc.attention_head_dim, int) else hc.attention_head_dim[0],
        norm_groups=hc.norm_num_groups)
    sd = {k: v for k, v in pipe.unet.state_dict().items()}
    unet_params = convert_diffusers_unet(sd, cfg)
    unet = UNet2DConditionModel(cfg, compute_dtype=jnp.float32)
    rng = np.random.RandomState(0)
    lat = rng.randn(1, hc.sample_size, hc.sample_size,
                    hc.in_channels).astype(np.float32)
    emb = rng.randn(1, 77, pipe.text_encoder.config.hidden_size).astype(np.float32)
    t = np.array([10], np.int32)
    ours = np.asarray(unet(unet_params, jnp.asarray(lat), jnp.asarray(t),
                           jnp.asarray(emb)))
    with torch.no_grad():
        ref = pipe.unet(torch.tensor(lat.transpose(0, 3, 1, 2)),
                        torch.tensor(t),
                        encoder_hidden_states=torch.tensor(emb)
                        ).sample.numpy().transpose(0, 2, 3, 1)
    err = float(np.max(np.abs(ours - ref)))
    if err > 5e-2:
        return _record(name, "FAILED", f"unet max abs err {err:.4f}")
    _record(name, "PASSED", f"unet max abs err {err:.5f}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gpt2", default="gpt2")
    ap.add_argument("--llama", default=None)
    ap.add_argument("--sd", default=None)
    args = ap.parse_args()

    check_causal_lm(args.gpt2, f"gpt2:{args.gpt2}")
    if args.llama:
        check_causal_lm(args.llama, f"llama:{args.llama}")
    if args.sd:
        check_stable_diffusion(args.sd)

    # any OTHER locally cached/vendored causal LM is free parity evidence —
    # verify everything the machine already has
    checked = {args.gpt2, args.llama}
    for model_name, path in _discover_local_snapshots():
        if model_name in checked or f"local:{model_name}" in RESULTS:
            continue
        try:
            with open(os.path.join(path, "config.json")) as f:
                archs = json.load(f).get("architectures") or []
        except Exception:
            continue
        if any(a.endswith("ForCausalLM") for a in archs):
            checked.add(model_name)
            check_causal_lm(path, f"local:{model_name}")

    RESULTS["_probe"] = {
        "status": "INFO",
        "detail": f"cache dirs probed: {_hf_cache_dirs()}; "
                  f"snapshots found: "
                  f"{[n for n, _ in _discover_local_snapshots()]}"}
    with open(os.path.join(_REPO, "CHECKPOINT_PARITY.json"), "w") as f:
        json.dump(RESULTS, f, indent=1)
    failed = [k for k, v in RESULTS.items() if v["status"] == "FAILED"]
    if failed:
        raise SystemExit(f"parity FAILED: {failed}")
    print("all runnable checks passed "
          f"({sum(v['status'] == 'SKIPPED' for v in RESULTS.values())} skipped)")


if __name__ == "__main__":
    main()
