#!/usr/bin/env python
"""SLO/alert-rule config lint (ISSUE 13 satellite).

Usage:
    python scripts/check_slo_rules.py [CONFIG.json ...]

Validates SLO configs against the typed rules in
``deepspeed_tpu.telemetry.slo.validate_slo_config``: unknown SLI names
in rules, unknown kinds/severities, missing per-kind fields, objectives
outside (0, 1), malformed windows (non-positive, short >= long), and
burn thresholds that can NEVER fire (burn > 1 / (1 - objective) — the
bad fraction caps at 1.0, so such a rule looks armed but is dead).

With no arguments the built-in :data:`DEFAULT_SLO_CONFIG` is validated
— the config every engine runs when none is supplied, so a bad default
fails CI before it ships. Since ISSUE 14 that default-config check also
runs as the ``slo-rules`` pass of the shared static-analysis framework
(deepspeed_tpu/analysis/passes/slo_rules.py, via scripts/dstpu_lint.py
in run_tier1.sh); this CLI stays for validating arbitrary config FILES
and its exit-code contract is pinned by tests.

Exit status: 0 = every config valid, 1 = problems (all listed), 2 =
unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*",
                   help="SLO config JSON files (default: validate the "
                        "built-in DEFAULT_SLO_CONFIG)")
    args = p.parse_args(argv)
    from deepspeed_tpu.telemetry.slo import (DEFAULT_SLO_CONFIG,
                                             validate_slo_config)

    targets = []
    if args.paths:
        for path in args.paths:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    cfg = json.load(f)
            except (OSError, ValueError) as e:
                print(f"check_slo_rules: cannot parse {path}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                return 2
            targets.append((path, cfg))
    else:
        targets.append(("<built-in DEFAULT_SLO_CONFIG>",
                        DEFAULT_SLO_CONFIG))
    rc = 0
    for name, cfg in targets:
        errors = validate_slo_config(cfg)
        if errors:
            rc = 1
            print(f"INVALID SLO config {name}:", file=sys.stderr)
            for e in errors:
                print(f"  {e}", file=sys.stderr)
        else:
            n_slis = len(cfg.get("slis", []))
            n_rules = len(cfg.get("rules", []))
            print(f"SLO config OK: {name} ({n_slis} SLI(s), "
                  f"{n_rules} rule(s))")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
