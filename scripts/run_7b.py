"""LLaMA-6.7B on ONE 16 GB chip — the BASELINE north-star scale.

Two halves (round-2 VERDICT missing #1):

1. SERVING: a 6.7B-param LLaMA-architecture model served int8 weight-only
   (~7 GB weights+scales in HBM) through the compiled prefill+decode
   engine; bf16 (13.4 GB weights) is attempted and reported if it fits
   beside the KV cache. Random-init weights — values don't change timing.

2. TRAINING (device fwd/bwd TFLOPs): a full 6.7B bf16 fwd/bwd needs
   ~27 GB (13.4 GB params + 13.4 GB grads) and cannot fit one 16 GB chip
   at any activation budget — MEMPLAN.md's 8-device plan is the real
   deployment. The transferable single-chip number is measured by the
   two-point layer-stack method: time fwd/bwd at L=2 and L=6 with the
   exact 6.7B layer geometry (d=4096, 32 heads, inter=11008, full 32k
   vocab + chunked CE head, remat), solve per-layer and head costs from
   the two measurements, and compose the 32-layer step time. FLOPs use
   the same 6*N+attn accounting as BENCH_1B3 (run_1b3_offload.py).

Phase isolation: the tunneled chip is shared — a transient
RESOURCE_EXHAUSTED from a neighbor's allocation poisons the whole JAX
client, not just the failing call. Each phase therefore runs in a FRESH
subprocess (clean client) and is retried up to --attempts times; the
parent composes BENCH_7B.json from the per-phase JSON results.

Writes BENCH_7B.json at the repo root.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

RESULT_TAG = "PHASE_RESULT:"


def serve_phase(dtype):
    import jax  # noqa: F401

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
    from deepspeed_tpu.utils import groups

    cfg = LlamaConfig.llama_7b()
    prompt_len, trials = 512, 5
    short_new, long_new = 8, 128  # decode cost by dual-length differencing
    # with the SAME lengths as bench.py / PROFILE_DECODE.md (one serving
    # methodology everywhere — round-4 VERDICT weak #4):
    # each generate() call carries ~90-110 ms of relay dispatch overhead
    # (PROFILE_DECODE.md methodology), which a (long - short) difference
    # cancels; both lengths share the same 128-padded KV allocation so the
    # per-step workload is identical
    rs = np.random.RandomState(0)

    def fresh():
        return rs.randint(0, cfg.vocab_size,
                          size=(1, prompt_len)).astype(np.int32)

    groups.reset()
    t0 = time.perf_counter()
    engine = deepspeed_tpu.init_inference(
        LlamaModel(cfg), dtype=dtype,
        max_out_tokens=prompt_len + long_new)
    engine.generate(fresh(), max_new_tokens=1)  # warm the prefill program
    engine.generate(fresh(), max_new_tokens=short_new)
    engine.generate(fresh(), max_new_tokens=long_new)
    build_s = time.perf_counter() - t0

    def timed(new_tokens):
        ids = fresh()
        t0 = time.perf_counter()
        engine.generate(ids, max_new_tokens=new_tokens)
        return time.perf_counter() - t0

    prefill = sorted(timed(1) for _ in range(trials))
    short = sorted(timed(short_new) for _ in range(trials))
    long_ = sorted(timed(long_new) for _ in range(trials))
    med = lambda xs: xs[len(xs) // 2]  # noqa: E731
    per_tok = (med(long_) - med(short)) / (long_new - short_new)
    out = {
        "prefill_p50_ms": round(med(prefill) * 1e3, 1),
        "prefill_best_ms": round(prefill[0] * 1e3, 1),
        "build_and_compile_s": round(build_s, 1),
    }
    if per_tok > 0:
        out["decode_ms_per_token"] = round(per_tok * 1e3, 3)
        out["decode_tokens_per_sec"] = round(1.0 / per_tok, 1)
    else:  # contention crossed the trial sets — don't fake a number
        out["decode_ms_per_token"] = None
        out["decode_tokens_per_sec"] = None
    return out


def train_phase(num_layers):
    """Best-of fwd/bwd step time for an L-layer 6.7B-geometry model, and
    its parameter count (grads reduced to per-leaf scalar sums on device,
    as run_1b3_offload.py phase 1)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    batch, seq = 1, 2048
    cfg = LlamaConfig(num_layers=num_layers, hidden_size=4096, num_heads=32,
                      max_seq_len=seq)
    model = LlamaModel(cfg, remat=True, remat_policy="dots_no_batch")

    def init_bf16(key):
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x, model.init(key))

    params = jax.jit(init_bf16)(jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq + 1)).astype(np.int32)
    mb = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    def loss_fn(p, b):
        loss, _ = model.apply(p, b, rngs=None, train=True)
        return loss

    grad_step = jax.jit(lambda p, b: jax.tree_util.tree_map(
        lambda g: jnp.sum(jnp.abs(g.astype(jnp.float32))),
        jax.grad(loss_fn)(p, b)))

    def run(k):
        o = None
        for _ in range(k):
            o = grad_step(params, mb)
        jax.device_get(jax.tree_util.tree_leaves(o)[0])

    run(1)  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run(4)
        best = min(best, (time.perf_counter() - t0) / 4)
    return {"step_sec": best, "n_params": int(n_params),
            "batch": batch, "seq_len": seq}


PHASES = {
    "serve_int8": lambda: serve_phase("int8"),
    "serve_bf16": lambda: serve_phase("bf16"),
    "train_l2": lambda: train_phase(2),
    "train_l6": lambda: train_phase(6),
}


def run_phase_isolated(name, attempts, timeout=1200):
    """Run one phase in fresh subprocesses until it succeeds."""
    last = None
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--phase", name],
                capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            last = f"timeout after {timeout}s"
        else:
            for line in proc.stdout.splitlines():
                if line.startswith(RESULT_TAG):
                    out = json.loads(line[len(RESULT_TAG):])
                    print(f"[{name}] attempt {attempt}: ok {json.dumps(out)}",
                          flush=True)
                    return out
            tail = (proc.stdout + proc.stderr)[-600:]
            last = (f"rc={proc.returncode}: "
                    f"{tail.splitlines()[-1] if tail else ''}")
        print(f"[{name}] attempt {attempt} failed: {last}", flush=True)
        if attempt + 1 < attempts:
            time.sleep(15)  # shared-chip contention: give the neighbor a beat
    return {"error": f"all {attempts} attempts failed; last: {last[:300]}"}


def compose(results):
    from deepspeed_tpu.models.llama import LlamaConfig

    out = {"metric": "llama_6b7_single_chip",
           "serving": {"prompt_len": 512, "decode_len": 64, "batch": 1,
                       "method": "dual_length_differencing(generate[128]-"
                                 "generate[8])/120, medians — the bench.py/"
                                 "PROFILE_DECODE.md methodology; int8 "
                                 "streams ALL block matmuls (qkv, wo, "
                                 "gate/up/down) through the manual-DMA "
                                 "kernel with in-kernel layer slicing",
                       "int8": results["serve_int8"],
                       "bf16": results["serve_bf16"]}}
    l2, l6 = results["train_l2"], results["train_l6"]
    if "error" in l2 or "error" in l6:
        out["training"] = {"error": l2.get("error") or l6.get("error")}
        return out
    t2, t6 = l2["step_sec"], l6["step_sec"]
    n2, n6 = l2["n_params"], l6["n_params"]
    batch, seq = l2["batch"], l2["seq_len"]
    per_layer = (t6 - t2) / 4.0
    head = t2 - 2.0 * per_layer  # embed + chunked-CE head + constant costs
    if head < 0:
        # Timing noise can push the extrapolated head cost negative; clamp
        # so the composed 32-layer time is not silently skewed downward.
        print(f"[train] WARNING: extrapolated head cost negative "
              f"({head*1e3:.2f} ms) — clamping to 0", flush=True)
        head = 0.0
    full = LlamaConfig.llama_7b(max_seq_len=seq)
    layers = full.num_layers
    t_model = head + layers * per_layer
    tok = batch * seq
    n_full = (full.vocab_size * full.hidden_size +            # embed (tied)
              (n6 - n2) // 4 * layers)                        # per-layer
    flops_per_tok = 6.0 * n_full + 12.0 * layers * full.hidden_size * seq
    tok_s = tok / t_model
    out["training"] = {
        "method": "two-point layer-stack composition (L=2, L=6; exact 6.7B "
                  "layer geometry, full 32k vocab, remat dots_no_batch)",
        "batch": batch, "seq_len": seq,
        "n_params": int(n_full),
        "stack_l2_step_ms": round(t2 * 1e3, 1),
        "stack_l6_step_ms": round(t6 * 1e3, 1),
        "per_layer_fwd_bwd_ms": round(per_layer * 1e3, 2),
        "head_embed_ms": round(head * 1e3, 2),
        "composed_32l_step_ms": round(t_model * 1e3, 1),
        "device_fwd_bwd_tokens_per_sec": round(tok_s, 1),
        "device_fwd_bwd_tflops": round(tok_s * flops_per_tok / 1e12, 1),
        "note": "full-model single-chip fwd/bwd is memory-infeasible "
                "(13.4 GB bf16 params + 13.4 GB bf16 grads > 16 GB HBM); "
                "MEMPLAN.md documents the 8-device training plan this "
                "composes into",
    }
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=sorted(PHASES))
    ap.add_argument("--attempts", type=int, default=3)
    args = ap.parse_args()
    if args.phase:
        result = PHASES[args.phase]()
        print(RESULT_TAG + json.dumps(result), flush=True)
        return
    results = {name: run_phase_isolated(name, args.attempts)
               for name in ("serve_int8", "serve_bf16",
                            "train_l2", "train_l6")}
    out = compose(results)
    with open(os.path.join(_REPO, "BENCH_7B.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "llama_6b7", "done": True}))


if __name__ == "__main__":
    main()
