"""GPT-2-1.3B serving latency on one chip — bf16 vs int8 weight-only.

The >=1B-param serving half of the BASELINE ladder ("the inference engine
serves the resulting checkpoint"): batch-1 prefill + per-token decode
latency through `init_inference`'s compiled prefill+decode programs.
Params are random-init ON DEVICE (weight values don't change the timing;
no tunnel transfer involved). Writes SERVE_1B3.json at the repo root.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.utils import groups

    cfg = GPT2Config.gpt2_1b3()
    prompt_len, decode_len, trials = 512, 64, 9
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(1, prompt_len)).astype(np.int32)
    out = {"metric": "gpt2_1b3_serving", "prompt_len": prompt_len,
           "decode_len": decode_len, "batch": 1}
    for dtype in ("bf16", "int8"):
        groups.reset()
        engine = deepspeed_tpu.init_inference(
            GPT2Model(cfg), dtype=dtype,
            max_out_tokens=prompt_len + decode_len + 1)
        engine.generate(ids, max_new_tokens=1)
        engine.generate(ids, max_new_tokens=decode_len + 1)

        def timed(new_tokens):
            t0 = time.perf_counter()
            engine.generate(ids, max_new_tokens=new_tokens)
            return time.perf_counter() - t0

        prefill = sorted(timed(1) for _ in range(trials))
        full = sorted(timed(decode_len + 1) for _ in range(trials))
        decode_best = full[0] - prefill[0]
        out[dtype] = {
            "prefill_p50_ms": round(prefill[len(prefill) // 2] * 1e3, 1),
            "prefill_best_ms": round(prefill[0] * 1e3, 1),
            "decode_ms_per_token": round(decode_best * 1e3 / decode_len, 3)
            if decode_best > 0 else None,
            "decode_tokens_per_sec": round(decode_len / decode_best, 1)
            if decode_best > 0 else None,
        }
        del engine
    print(json.dumps(out))
    with open(os.path.join(_REPO, "SERVE_1B3.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
