"""Training-throughput sweep on the real chip: (attn_impl, remat, mb x gas).

Dogfoods the bench methodology (best-of-windows, see bench.py) across the
knobs VERDICT r1 called out: whether the Pallas FA2 kernel beats XLA dense
attention, whether remat is needed at all at 125M, and the microbatch split.
Prints one JSON line per config; run me on the tunnel chip.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_config(attn_impl, remat, remat_policy, batch, gas, loss_chunk=0,
               steps=8, windows=3):
    import dataclasses

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.utils import groups

    groups.reset()
    seq = 1024
    cfg = GPT2Config.gpt2_125m()
    if loss_chunk:
        cfg = dataclasses.replace(cfg, loss_chunk=loss_chunk)
    model = GPT2Model(cfg, remat=remat, remat_policy=remat_policy,
                      attn_impl=attn_impl)
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": batch * gas,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
        "zero_optimization": {"stage": 0},
    })
    rng = np.random.RandomState(0)

    def make_batch():
        ids = rng.randint(0, cfg.vocab_size, size=(gas, batch, seq + 1)).astype(np.int32)
        return {"input_ids": ids[:, :, :-1], "labels": ids[:, :, 1:]}

    for _ in range(2):
        loss = engine.train_batch_from_stacked(make_batch())
    float(jax.device_get(loss))
    best_dt = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch_from_stacked(make_batch())
        float(jax.device_get(loss))
        best_dt = min(best_dt, time.perf_counter() - t0)
    toks = batch * gas * seq * steps / best_dt
    return toks


def main():
    grid = [
        # (attn_impl, remat, policy, mb, gas[, loss_chunk])
        ("dense", True, "dots_no_batch", 8, 8),
        ("dense", True, "dots_no_batch", 16, 4),
        ("dense", True, "dots_no_batch", 4, 16),   # r1 champion re-measure
        ("flash", True, "dots_no_batch", 8, 8),
        ("dense", True, "nothing", 8, 8),
        ("dense", True, "dots_no_batch", 32, 2),
        ("dense", True, "dots_no_batch", 8, 8, 512),   # chunked LM loss
        ("flash", False, None, 8, 8),                  # sweep-1 runner-up
        ("flash", True, "save_attn", 4, 16),           # idx 8: selective remat
        ("flash", True, "save_attn", 8, 8),            # idx 9
        ("flash", True, "save_attn", 16, 4),           # idx 10
    ]
    if len(sys.argv) > 1:  # allow running a subset: indices as args
        grid = [grid[int(i)] for i in sys.argv[1:]]
    results = []
    for g in grid:
        try:
            toks = run_config(*g)
            results.append((g, round(toks)))
        except Exception as e:
            results.append((g, f"ERROR {type(e).__name__}: {e}"))
        print(json.dumps({"config": list(results[-1][0]), "tok_s": results[-1][1]}),
              flush=True)
    best = max((r for r in results if isinstance(r[1], (int, float))),
               key=lambda r: r[1], default=None)
    print("BEST:", best)


if __name__ == "__main__":
    main()
