"""Training-throughput sweep on the real chip: (attn_impl, remat, mb x gas).

Dogfoods the bench methodology (best-of-windows, see bench.py) across the
knobs VERDICT r1 called out: whether the Pallas FA2 kernel beats XLA dense
attention, whether remat is needed at all at 125M, and the microbatch split.
Prints one JSON line per config; run me on the tunnel chip.
"""

from __future__ import annotations

import json
import os
import sys


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_config(attn_impl, remat, remat_policy, batch, gas, loss_chunk=0,
               steps=8, windows=3):
    from scripts.bench_common import train_tokens_per_sec

    return train_tokens_per_sec(
        attn_impl=attn_impl, remat=remat, remat_policy=remat_policy,
        batch=batch, gas=gas, loss_chunk=loss_chunk, steps=steps,
        windows=windows)


def main():
    grid = [
        # (attn_impl, remat, policy, mb, gas[, loss_chunk])
        ("dense", True, "dots_no_batch", 8, 8),
        ("dense", True, "dots_no_batch", 16, 4),
        ("dense", True, "dots_no_batch", 4, 16),   # r1 champion re-measure
        ("flash", True, "dots_no_batch", 8, 8),
        ("dense", True, "nothing", 8, 8),
        ("dense", True, "dots_no_batch", 32, 2),
        ("dense", True, "dots_no_batch", 8, 8, 512),   # chunked LM loss
        ("flash", False, None, 8, 8),                  # sweep-1 runner-up
        ("flash", True, "save_attn", 4, 16),           # idx 8: selective remat
        ("flash", True, "save_attn", 8, 8),            # idx 9
        ("flash", True, "save_attn", 16, 4),           # idx 10
        ("flash", False, None, 16, 4),                 # idx 11
        ("flash", False, None, 16, 4, 512),            # idx 12: chunked CE
        ("flash", False, None, 32, 2, 512),            # idx 13
        ("flash", False, None, 8, 8, 512),             # idx 14
    ]
    if len(sys.argv) > 1:  # allow running a subset: indices as args
        grid = [grid[int(i)] for i in sys.argv[1:]]
    results = []
    for g in grid:
        try:
            toks = run_config(*g)
            results.append((g, round(toks)))
        except Exception as e:
            results.append((g, f"ERROR {type(e).__name__}: {e}"))
        print(json.dumps({"config": list(results[-1][0]), "tok_s": results[-1][1]}),
              flush=True)
    best = max((r for r in results if isinstance(r[1], (int, float))),
               key=lambda r: r[1], default=None)
    print("BEST:", best)


if __name__ == "__main__":
    main()
