#!/usr/bin/env python
"""Metric-name drift lint: README docs vs telemetry call sites.

Usage:
    python scripts/check_metric_names.py [--list]

PR 3's contract is that every counter/gauge/histogram/event the code
emits is documented in the README (operators grep the README, not the
source), and PRs 4-11 each grew the namespace — by hand, in both
places. This lint (ISSUE 11 satellite) makes the contract mechanical;
since ISSUE 14 the collection/matching logic lives in the shared
static-analysis framework as the ``metric-names`` pass
(deepspeed_tpu/analysis/passes/metric_names.py) and this script is a
thin CLI shim over it — same flags, same output, same exit codes:

  * CODE side: an AST walk over ``deepspeed_tpu/`` collects the first
    string argument of every ``counter(...)``, ``gauge(...)``,
    ``histogram(...)``, ``event(...)``, ``record_event(...)`` and the
    router's ``_count/_gauge/_observe`` wrappers. f-strings become
    wildcard patterns (``f"serving/ttft_ms/p{c}"`` ->
    ``serving/ttft_ms/p*``).
  * DOC side: every backticked token in README.md that looks like a
    metric name (``<prefix>/...`` for the known prefixes), with
    ``<placeholder>`` segments normalized to ``*``.

Failure modes (exit 1, both listed):
  * UNDOCUMENTED — emitted by code, absent from the README;
  * STALE       — documented in the README, emitted by nothing.

Wired into tier-1 via tests/unit/telemetry/test_spans.py and
scripts/run_tier1.sh (through dstpu_lint.py). No longer stdlib-only:
importing the framework pass pulls in the deepspeed_tpu package (and
jax) — run with JAX_PLATFORMS=cpu where no accelerator is configured.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.analysis.passes.metric_names import (  # noqa: E402
    _covered, code_names, drift, readme_names)

__all__ = ["code_names", "readme_names", "_covered", "main"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=None,
                    help="repo root (default: this script's parent)")
    ap.add_argument("--list", action="store_true",
                    help="print every name on both sides")
    args = ap.parse_args(argv)
    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    code = code_names(os.path.join(root, "deepspeed_tpu"))
    docs = readme_names(os.path.join(root, "README.md"))
    if args.list:
        print("== code ==")
        for n in sorted(code):
            print(f"  {n}  ({code[n][0]})")
        print("== README ==")
        for n in sorted(docs):
            print(f"  {n}  (line {docs[n][0]})")
    undocumented, stale = drift(code, docs)
    rc = 0
    if undocumented:
        rc = 1
        print("UNDOCUMENTED metric names (emitted by code, missing from "
              "README.md):", file=sys.stderr)
        for n in sorted(undocumented):
            print(f"  {n}  ({undocumented[n][0]})", file=sys.stderr)
    if stale:
        rc = 1
        print("STALE metric names (documented in README.md, emitted by "
              "nothing):", file=sys.stderr)
        for n in sorted(stale):
            print(f"  {n}  (README line {stale[n][0]})", file=sys.stderr)
    if rc == 0:
        print(f"metric names OK: {len(code)} code name(s) <-> "
              f"{len(docs)} documented name(s)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
