#!/usr/bin/env python
"""Metric-name drift lint: README docs vs telemetry call sites.

Usage:
    python scripts/check_metric_names.py [--list]

PR 3's contract is that every counter/gauge/histogram/event the code
emits is documented in the README (operators grep the README, not the
source), and PRs 4-11 each grew the namespace — by hand, in both
places. This lint (ISSUE 11 satellite) makes the contract mechanical:

  * CODE side: an AST walk over ``deepspeed_tpu/`` collects the first
    string argument of every ``counter(...)``, ``gauge(...)``,
    ``histogram(...)``, ``event(...)``, ``record_event(...)`` and the
    router's ``_count/_gauge/_observe`` wrappers. f-strings become
    wildcard patterns (``f"serving/ttft_ms/p{c}"`` ->
    ``serving/ttft_ms/p*``).
  * DOC side: every backticked token in README.md that looks like a
    metric name (``<prefix>/...`` for the known prefixes), with
    ``<placeholder>`` segments normalized to ``*``.

Failure modes (exit 1, both listed):
  * UNDOCUMENTED — emitted by code, absent from the README;
  * STALE       — documented in the README, emitted by nothing.

Wired into tier-1 via tests/unit/telemetry/test_spans.py and
scripts/run_tier1.sh. Stdlib only.
"""

from __future__ import annotations

import argparse
import ast
import fnmatch
import os
import re
import sys

PREFIXES = ("train", "serving", "fabric", "resilience", "device",
            "checkpoint", "elastic", "slo", "telemetry")
_NAME_RE = re.compile(
    r"^(?:%s)/[A-Za-z0-9_][A-Za-z0-9_/<>*-]*$" % "|".join(PREFIXES))
# methods whose first string argument is a metric/event name
_METHODS = {"counter", "gauge", "histogram", "event", "record_event",
            "_count", "_gauge", "_observe"}


def _pattern_of(node) -> str | None:
    """Metric-name pattern of a str/f-string AST node (formatted pieces
    become '*'), or None for non-strings."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def code_names(root: str) -> dict:
    """{pattern: [file:line, ...]} over every telemetry call site."""
    out: dict = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                func = node.func
                name = (func.attr if isinstance(func, ast.Attribute)
                        else func.id if isinstance(func, ast.Name)
                        else None)
                if name not in _METHODS:
                    continue
                pat = _pattern_of(node.args[0])
                if pat is None or not _NAME_RE.match(pat):
                    continue
                out.setdefault(pat, []).append(
                    f"{os.path.relpath(path, os.path.dirname(root))}:"
                    f"{node.lineno}")
    return out


def readme_names(readme_path: str) -> dict:
    """{pattern: [line_no, ...]} over backticked metric-like tokens,
    ``<placeholder>`` segments normalized to ``*``."""
    out: dict = {}
    with open(readme_path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            for tok in re.findall(r"`([^`]+)`", line):
                if not _NAME_RE.match(tok):
                    continue
                pat = re.sub(r"<[^>]*>", "*", tok)
                out.setdefault(pat, []).append(i)
    return out


def _covered(name: str, patterns) -> bool:
    """A name (possibly itself a wildcard pattern) is covered when any
    pattern on the other side matches it — either direction, so
    ``serving/ttft_ms/p*`` (code f-string) pairs with
    ``serving/ttft_ms/p<class>`` (doc placeholder)."""
    for p in patterns:
        if p == name or fnmatch.fnmatchcase(name, p) \
                or fnmatch.fnmatchcase(p, name):
            return True
    return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=None,
                    help="repo root (default: this script's parent)")
    ap.add_argument("--list", action="store_true",
                    help="print every name on both sides")
    args = ap.parse_args(argv)
    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    code = code_names(os.path.join(root, "deepspeed_tpu"))
    docs = readme_names(os.path.join(root, "README.md"))
    if args.list:
        print("== code ==")
        for n in sorted(code):
            print(f"  {n}  ({code[n][0]})")
        print("== README ==")
        for n in sorted(docs):
            print(f"  {n}  (line {docs[n][0]})")
    undocumented = {n: sites for n, sites in code.items()
                    if not _covered(n, docs)}
    stale = {n: lines for n, lines in docs.items()
             if not _covered(n, code)}
    rc = 0
    if undocumented:
        rc = 1
        print("UNDOCUMENTED metric names (emitted by code, missing from "
              "README.md):", file=sys.stderr)
        for n in sorted(undocumented):
            print(f"  {n}  ({undocumented[n][0]})", file=sys.stderr)
    if stale:
        rc = 1
        print("STALE metric names (documented in README.md, emitted by "
              "nothing):", file=sys.stderr)
        for n in sorted(stale):
            print(f"  {n}  (README line {stale[n][0]})", file=sys.stderr)
    if rc == 0:
        print(f"metric names OK: {len(code)} code name(s) <-> "
              f"{len(docs)} documented name(s)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
