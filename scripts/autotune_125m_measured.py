"""Dogfood the MEASURED autotuner path on the real chip (round-4 VERDICT #7).

The analytic artifact (AUTOTUNE_125M.json, scripts/autotune_125m.py) ranks
candidates with a compile-time cost model; the reference's autotuner runs
real experiments instead (`/root/reference/deepspeed/autotuning/
autotuner.py:664` + scheduler.py). This script drives the SAME subprocess
experiment contract the CLI uses (autotuning/cli.py run_experiment:
DSTPU_AUTOTUNING_CONFIG overrides in, DSTPU_AUTOTUNING_RESULT metric
out) over a small on-chip space, then reports the analytic model's rank
correlation against the measured ranking. Each child OWNS its
measurement — value-fenced steps, self-written result file — because the
engine's ThroughputTimer brackets the async dispatch on this relay
(runtime/engine.py now fences armed steps too, but the child's own
timing keeps the artifact independent of engine internals).

Writes AUTOTUNE_125M_MEASURED.json at the repo root.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

GAS = 8
SEQ = 1024
SPACE = [{"zero_optimization": {"stage": stage},
          "train_micro_batch_size_per_gpu": mb,
          "gradient_accumulation_steps": GAS,
          "train_batch_size": mb * GAS}
         for stage in (0, 2) for mb in (2, 4, 8)]


def child():
    """One experiment: train GPT-2-125M on the chip. The child disarms the
    engine's self-report hook (pops DSTPU_AUTOTUNING_RESULT) and writes
    the value-fenced metric itself — see the module docstring."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.utils import groups

    groups.reset()
    cfg = GPT2Config.gpt2_125m()
    model = GPT2Model(cfg, attn_impl="flash")
    # base config; DSTPU_AUTOTUNING_CONFIG overrides merge inside
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8 * GAS,
        "gradient_accumulation_steps": GAS,
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
    })
    import time as _time

    # the engine's own ThroughputTimer wraps the (async) train_batch CALL,
    # so on this relay it self-reports dispatch rate — physically
    # impossible numbers (36M tokens/sec observed). The child therefore
    # owns the measurement: value-fenced steps, steps 3+ timed, and it
    # writes the result file itself (the engine hook is disarmed by
    # removing the env var it checks).
    result_path = os.environ.pop("DSTPU_AUTOTUNING_RESULT", None)
    mb = engine.config.train_micro_batch_size_per_gpu
    rng = np.random.RandomState(0)

    def step():
        ids = rng.randint(0, cfg.vocab_size,
                          size=(GAS, mb, SEQ + 1)).astype(np.int32)
        loss = engine.train_batch_from_stacked(
            {"input_ids": ids[:, :, :-1], "labels": ids[:, :, 1:]})
        float(jax.device_get(loss))

    for _ in range(3):      # compile + warm
        step()
    n = 4
    t0 = _time.perf_counter()
    for _ in range(n):
        step()
    dt = _time.perf_counter() - t0
    samples_per_sec = n * mb * GAS / dt
    if result_path:
        with open(result_path, "w") as f:
            json.dump({"metric": samples_per_sec,
                       "unit": "samples/sec (value-fenced)"}, f)
    raise SystemExit(0)


def analytic_estimates():
    """Cost-model tokens/sec for the SAME points (single-device plan)."""
    import jax.numpy as jnp

    from deepspeed_tpu.autotuning import Autotuner
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    model = GPT2Model(GPT2Config.gpt2_125m(), compute_dtype=jnp.bfloat16)
    tuner = Autotuner(model, {
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "gradient_clipping": 1.0,
    }, seq_len=SEQ, vocab_size=50257, hbm_bytes=16e9,
        peak_flops=197e12, hbm_bw=819e9)
    tuner.tune(zero_stages=(0, 2), space={
        "micro_batch": [2, 4, 8], "gas": [GAS],
        "offload": [False], "remat": [None]})
    out = {}
    for r in tuner.results:
        out[(r.zero_stage, r.micro_batch)] = r.tokens_per_sec
    return out


def main():
    if "--child" in sys.argv:
        child()
        return
    if "--analytic" in sys.argv:
        est = analytic_estimates()
        print("ANALYTIC_JSON " + json.dumps(
            [[k[0], k[1], v] for k, v in est.items()]))
        return
    from deepspeed_tpu.autotuning.cli import run_experiment

    results_dir = os.path.join(_REPO, "autotuning_results_measured")
    cmd = [sys.executable, os.path.abspath(__file__), "--child"]
    trials = []
    for i, overrides in enumerate(SPACE):
        exp_dir = os.path.join(results_dir, f"exp_{i}")
        metric = run_experiment(cmd, overrides, exp_dir, timeout_s=900.0)
        mb = overrides["train_micro_batch_size_per_gpu"]
        stage = overrides["zero_optimization"]["stage"]
        tok_s = metric * SEQ if metric else None  # samples/sec -> tokens/sec
        trials.append({"zero_stage": stage, "micro_batch": mb, "gas": GAS,
                       "measured_samples_per_sec": metric,
                       "measured_tokens_per_sec": tok_s})
        print(f"[measured] stage={stage} mb={mb}: {tok_s}", flush=True)

    # analytic estimates in a forced-CPU subprocess (the cost model AOT-
    # compiles on the virtual mesh, same bootstrap as autotune_125m.py)
    import subprocess

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["DSTPU_ACCELERATOR"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    est = {}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--analytic"],
            env=env, capture_output=True, text=True, timeout=1800)
        for line in proc.stdout.splitlines():
            if line.startswith("ANALYTIC_JSON "):
                for stage, mb, v in json.loads(line[len("ANALYTIC_JSON "):]):
                    est[(stage, mb)] = v
        if not est:
            print(f"[analytic] child rc={proc.returncode}, no estimates; "
                  f"stderr tail: {proc.stderr[-300:]}", flush=True)
    except Exception as e:
        # never discard the on-chip measurements because the CPU cost-model
        # pass hung/crashed — rank correlation just degrades to null
        print(f"[analytic] failed: {type(e).__name__}: {e}", flush=True)
    for t in trials:
        t["analytic_tokens_per_sec"] = est.get(
            (t["zero_stage"], t["micro_batch"]))

    ok = [t for t in trials if t["measured_tokens_per_sec"]
          and t["analytic_tokens_per_sec"]]
    rho = None
    if len(ok) >= 3:
        def ranks(vals):
            order = np.argsort(np.argsort(vals))
            return order.astype(float)
        m = ranks([t["measured_tokens_per_sec"] for t in ok])
        a = ranks([t["analytic_tokens_per_sec"] for t in ok])
        d = m - a
        n = len(ok)
        rho = float(1 - 6 * np.sum(d * d) / (n * (n * n - 1)))
    best = max((t for t in trials if t["measured_tokens_per_sec"]),
               key=lambda t: t["measured_tokens_per_sec"], default=None)
    out = {
        "metric": "autotune_125m_measured",
        "space": "zero_stage x micro_batch (gas=8, seq=1024, flash attn)",
        "trials": trials,
        "best_measured": best,
        "spearman_rank_correlation_analytic_vs_measured": rho,
        "note": "measured via the CLI's subprocess experiment contract "
                "(DSTPU_AUTOTUNING_CONFIG/RESULT); each child times "
                "value-fenced steps itself (async dispatch makes timer-"
                "bracketed dispatch rates physically impossible — "
                "PROFILE_DECODE.md methodology). Analytic numbers are the "
                "cost model's ABSOLUTE estimates — known optimistic (no "
                "dispatch/bubble model); the rank correlation is the "
                "dogfood question. measured_tokens_per_sec includes the "
                "per-step fence (~0.1s), so it under-reads the async "
                "pipeline rate bench.py measures (93.5k at stage0/mb8).",
    }
    with open(os.path.join(_REPO, "AUTOTUNE_125M_MEASURED.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "autotune_125m_measured", "done": True,
                      "rho": rho}))


if __name__ == "__main__":
    main()
