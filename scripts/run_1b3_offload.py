"""GPT-2-1.3B bf16 training on ONE chip with ZeRO-Offload host optimizer.

The point (reference docs/_posts/2021-03-08-zero3-offload.md): 1.3B params
need 15.7GB of fp32 master+Adam state — more than this chip's HBM — so the
optimizer state lives in host RAM (HostOffloadOptimizer) while the device
holds only bf16 compute params + rematted activations.

This dev environment reaches the chip through a tunnel whose host<->device
link is ~7-17 MB/s (vs GB/s PCIe on a real TPU host), so the end-to-end
step is transfer-dominated HERE. The script therefore measures each phase
separately — device fwd/bwd throughput (chip-limited, the number that
transfers to real hardware), host Adam time, and the transfer cost at the
measured link rate — and reports an end-to-end projection for a real
10 GB/s host link next to the measured-here number.

Phases run in fresh subprocesses with retries (the shared tunnel chip can
ResourceExhaust transiently and poison the client — bench_common
.run_phase_isolated; round 4: a monolithic run died 40 min in, in
phase 2).

Run on the tunnel chip: `python scripts/run_1b3_offload.py`.
Writes BENCH_1B3.json at the repo root.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scripts.bench_common import emit_phase_result, run_phase_isolated  # noqa: E402

BATCH, SEQ, GAS = 2, 1024, 4


def _model():
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config.gpt2_1b3()
    return cfg, GPT2Model(cfg, remat=True, remat_policy="dots_no_batch")


def phase_fwd_bwd():
    """Device-side fwd/bwd throughput (no optimizer state moves)."""
    import jax
    import jax.numpy as jnp

    cfg, model = _model()
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      size=(BATCH, SEQ + 1)).astype(np.int32)
    mb = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    def loss_fn(p, b):
        loss, _ = model.apply(p, b, rngs=None, train=True)
        return loss

    grad_step = jax.jit(lambda p, b: jax.tree_util.tree_map(
        lambda g: jnp.sum(jnp.abs(g.astype(jnp.float32))),
        jax.grad(loss_fn)(p, b)))

    def run_fwd_bwd(k=4):
        out = None
        for _ in range(k):
            out = grad_step(params, mb)
        jax.device_get(jax.tree_util.tree_leaves(out)[0])

    run_fwd_bwd(1)  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run_fwd_bwd(4)
        best = min(best, (time.perf_counter() - t0) / 4)
    dev_tok_s = BATCH * SEQ / best
    return {"n_params": int(n_params),
            "device_fwd_bwd_tokens_per_sec": round(dev_tok_s, 1),
            "device_fwd_bwd_tflops": round(
                dev_tok_s * 6 * n_params / 1e12, 1)}


def phase_offload_e2e():
    """One REAL end-to-end offload engine step + host Adam in isolation."""
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.utils import groups

    cfg, model = _model()
    groups.reset()
    rng = np.random.RandomState(0)
    t_init0 = time.perf_counter()
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": BATCH * GAS,
        "gradient_accumulation_steps": GAS,
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu"}},
    })
    t_init = time.perf_counter() - t_init0
    assert engine.offload_optimizer, "engine must be in host-offload mode"

    def one_step():
        ids = rng.randint(0, cfg.vocab_size,
                          size=(GAS, BATCH, SEQ + 1)).astype(np.int32)
        b = {"input_ids": ids[:, :, :-1], "labels": ids[:, :, 1:]}
        t0 = time.perf_counter()
        loss = float(jax.device_get(engine.train_batch_from_stacked(b)))
        return loss, time.perf_counter() - t0

    _, t_cold = one_step()          # includes fwd/bwd compile
    loss, t_step = one_step()       # warm end-to-end step

    # host Adam cost in isolation: time the REAL host step (bias
    # correction, native kernel, master->compute-image conversion) on
    # host-resident zero grads — no tunnel transfer involved. Runs after
    # all training measurements; it advances the optimizer state one
    # no-op step, which nothing downstream consumes.
    zero_grads = {n: np.zeros_like(m)
                  for n, m in engine._host_opt.master.items()}
    t_host_adam = float("inf")   # best-of-3: first call pays page faults;
    for _ in range(3):           # co-tenant CPU noise is real
        t0 = time.perf_counter()
        engine._host_opt.step(zero_grads, 1e-4)
        t_host_adam = min(t_host_adam, time.perf_counter() - t0)

    # quantify the host Adam against what THIS host can actually move
    # (round-4 VERDICT weak #5: "3-4 GB/s effective, unexplained"): the
    # fused one-pass sweep touches ~26 bytes/param (grad f32 read, master
    # f32 r/w, m f32 r/w, v f32 r/w, bf16 image write + the f32->bf16
    # convert), so effective GB/s = 26 * n / t. Reference point: a numpy
    # COPY on the same cores (2 streams exactly — a numpy triad
    # materializes temporaries and would move ~5 streams while crediting
    # 3, overstating the Adam kernel's relative efficiency).
    n_host = sum(int(m.size) for m in engine._host_opt.master.values())
    adam_bytes = 26.0 * n_host
    n_threads = int(os.environ.get("OMP_NUM_THREADS", 0)) or os.cpu_count()
    a = np.zeros(64 * 1024 * 1024 // 8)  # 64 MB
    b_ = np.ones_like(a)
    t_copy = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        a[:] = b_
        t_copy = min(t_copy, time.perf_counter() - t0)
    stream_gbps = 2 * a.nbytes / t_copy / 1e9

    # measured tunnel link rate (for the projection)
    probe = jnp.ones((16, 1024, 1024), jnp.float32)  # 64MB
    jax.block_until_ready(probe)
    t0 = time.perf_counter()
    jax.device_get(probe)
    d2h_bps = probe.nbytes / (time.perf_counter() - t0)
    return {"e2e_step_loss": round(loss, 4),
            "e2e_tokens_per_sec_via_tunnel": round(
                BATCH * GAS * SEQ / t_step, 2),
            "e2e_cold_step_sec": round(t_cold, 1),
            "host_adam_step_sec": round(t_host_adam, 2),
            "host_adam_gbps": round(adam_bytes / t_host_adam / 1e9, 2),
            "host_adam_threads": n_threads,
            "host_stream_copy_gbps": round(stream_gbps, 2),
            "host_adam_note": (
                "this sandbox exposes ONE core: the fused sweep is "
                "core-compute-bound there (~10+ flops/param of Adam math "
                "+ bf16 decode per 26 bytes), not bandwidth-bound; the "
                "OMP-parallel kernel scales with cores on a real host"),
            "engine_init_sec": round(t_init, 1),
            "tunnel_d2h_mb_per_sec": round(d2h_bps / 1e6, 1)}


PHASES = {"fwd_bwd": phase_fwd_bwd, "offload_e2e": phase_offload_e2e}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=sorted(PHASES))
    ap.add_argument("--attempts", type=int, default=3)
    args = ap.parse_args()
    if args.phase:
        emit_phase_result(PHASES[args.phase]())
        return
    me = os.path.abspath(__file__)
    p1 = run_phase_isolated(me, "fwd_bwd", args.attempts, timeout=3000)
    p2 = run_phase_isolated(me, "offload_e2e", args.attempts, timeout=3000)
    out = {"metric": "gpt2_1b3_offload"}
    if "error" in p1 or "error" in p2:
        out["error"] = p1.get("error") or p2.get("error")
        out.update({k: v for p in (p1, p2) for k, v in p.items()
                    if k != "error"})
    else:
        n_params = p1["n_params"]
        dev_tok_s = p1["device_fwd_bwd_tokens_per_sec"]
        t_host_adam = p2["host_adam_step_sec"]
        # real-host projection: grads f32 down + bf16 params up at 10 GB/s,
        # host Adam overlaps gas-scan compute on a real machine;
        # conservative: add transfer + host step serially
        bytes_per_step = 4.0 * n_params + 2.0 * n_params
        proj_step = (BATCH * GAS * SEQ / dev_tok_s) + \
            bytes_per_step / 10e9 + t_host_adam
        out.update(p1)
        out.update({"host_state_gb": round(12.0 * n_params / 1e9, 2),
                    "hbm_if_no_offload_gb": round(14.0 * n_params / 1e9, 2)})
        out.update(p2)
        out["projected_tokens_per_sec_at_10GBps_host_link"] = round(
            BATCH * GAS * SEQ / proj_step, 1)
        out["zero_stage"] = 2
        out["offload"] = "cpu"
        out["note"] = ("end-to-end rate here is tunnel-transfer-bound "
                       "(dev env); device fwd/bwd rate + projection are "
                       "the transferable numbers")
    print(json.dumps(out))
    with open(os.path.join(_REPO, "BENCH_1B3.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
