"""Long-context training sweep on one chip: flash attention vs dense.

Long-context is first-class here (ring/Ulysses shard beyond one chip; this
script shows the single-chip half): Pallas flash attention keeps activation
memory linear in T, so training seq lengths where dense attention's T^2
buffers OOM the 16 GB chip. GPT-2-125M, bf16, remat save_attn.
Writes LONGSEQ.json at the repo root.
"""

from __future__ import annotations

import json
import os
import sys


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def run(seq: int, attn: str, batch: int, gas: int, steps=4, windows=3):
    from scripts.bench_common import train_tokens_per_sec

    return round(train_tokens_per_sec(
        attn_impl=attn, remat=(attn != "flash"),
        remat_policy=None if attn == "flash" else "dots_no_batch",
        batch=batch, gas=gas, seq=seq, steps=steps, windows=windows), 1)


def main():
    grid = [
        # (seq, attn, micro_batch, gas) — tokens/step held at 32k
        (2048, "flash", 2, 8),
        (2048, "dense", 2, 8),
        (4096, "flash", 1, 8),
        (4096, "dense", 1, 8),
        (8192, "flash", 1, 4),
        (8192, "dense", 1, 4),
    ]
    out = {"metric": "gpt2_125m_longseq_train", "unit": "tokens/sec/chip",
           "results": []}
    for seq, attn, mb, gas in grid:
        try:
            toks = run(seq, attn, mb, gas)
            rec = {"seq": seq, "attn": attn, "micro_batch": mb, "gas": gas,
                   "tokens_per_sec": toks}
        except Exception as e:
            msg = str(e)
            rec = {"seq": seq, "attn": attn, "micro_batch": mb, "gas": gas,
                   "error": ("OOM" if "memory" in msg.lower() else
                             f"{type(e).__name__}") ,
                   "detail": msg[:160]}
        out["results"].append(rec)
        print(json.dumps(rec), flush=True)
    with open(os.path.join(_REPO, "LONGSEQ.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
