// dstpu_cpu_adam — vectorized host optimizer kernels for ZeRO-Offload.
//
// Reference analog: csrc/adam/cpu_adam.cpp + csrc/adagrad/cpu_adagrad.cpp —
// the optimizer step for host-resident (offloaded) state.  The reference
// hand-writes AVX2/AVX512 intrinsics; here the loops are written so the
// compiler auto-vectorizes them (built with -O3 -mavx2/-mavx512f -fopenmp by
// the native op builder), which reaches the same memory-bound roofline on
// modern toolchains without per-ISA code paths.
//
// All arrays are dense fp32 host buffers (numpy-owned).  The fp32→bf16 copy
// kernel produces the compute-dtype image that gets pushed back to the
// device after the step (the reference's fp16 param copy, cpu_adam.h).

#include <cmath>
#include <cstddef>
#include <cstdint>

extern "C" {

// One Adam/AdamW step over a flat shard.  step is the 1-based step count
// AFTER this update (bias correction uses it directly).
void dstpu_adam_step(float* params, const float* grads, float* exp_avg,
                     float* exp_avg_sq, uint64_t n, int64_t step, float lr,
                     float beta1, float beta2, float eps, float weight_decay,
                     int adamw_mode, int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float one_m_b1 = 1.0f - beta1;
  const float one_m_b2 = 1.0f - beta2;
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < (int64_t)n; ++i) {
    float g = grads[i];
    if (weight_decay > 0.0f && !adamw_mode) g += weight_decay * params[i];
    float m = beta1 * exp_avg[i] + one_m_b1 * g;
    float v = beta2 * exp_avg_sq[i] + one_m_b2 * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float update = (m / bc1) / (std::sqrt(v / bc2) + eps);
    if (weight_decay > 0.0f && adamw_mode) update += weight_decay * params[i];
    params[i] -= lr * update;
  }
}

void dstpu_adagrad_step(float* params, const float* grads, float* sum_sq,
                        uint64_t n, float lr, float eps, float weight_decay) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < (int64_t)n; ++i) {
    float g = grads[i];
    if (weight_decay > 0.0f) g += weight_decay * params[i];
    float s = sum_sq[i] + g * g;
    sum_sq[i] = s;
    params[i] -= lr * g / (std::sqrt(s) + eps);
  }
}

// fp32 → bf16 (round-to-nearest-even), for pushing compute-dtype params back
// to the device.
void dstpu_copy_f32_to_bf16(const float* src, uint16_t* dst, uint64_t n) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < (int64_t)n; ++i) {
    uint32_t bits;
    __builtin_memcpy(&bits, &src[i], 4);
    if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x007FFFFFu)) {
      dst[i] = 0x7FC0;  // NaN: RNE carry could silently flip it to +/-0 or Inf
      continue;
    }
    uint32_t lsb = (bits >> 16) & 1u;
    uint32_t rounded = bits + 0x7FFFu + lsb;
    dst[i] = (uint16_t)(rounded >> 16);
  }
}

}  // extern "C"
