// dstpu_cpu_adam — vectorized host optimizer kernels for ZeRO-Offload.
//
// Reference analog: csrc/adam/cpu_adam.cpp + csrc/adagrad/cpu_adagrad.cpp —
// the optimizer step for host-resident (offloaded) state.  The reference
// hand-writes AVX2/AVX512 intrinsics; here the loops are written so the
// compiler auto-vectorizes them (built with -O3 -mavx2/-mavx512f -fopenmp by
// the native op builder), which reaches the same memory-bound roofline on
// modern toolchains without per-ISA code paths.
//
// All arrays are dense fp32 host buffers (numpy-owned).  The fp32→bf16 copy
// kernel produces the compute-dtype image that gets pushed back to the
// device after the step (the reference's fp16 param copy, cpu_adam.h).

#include <cmath>
#include <cstddef>
#include <cstdint>

namespace {

inline float bf16_to_f32(uint16_t u) {
  uint32_t bits = ((uint32_t)u) << 16;  // widening is exact
  float f;
  __builtin_memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  __builtin_memcpy(&bits, &f, 4);
  if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x007FFFFFu))
    return 0x7FC0;  // NaN: RNE carry could silently flip it to +/-0 or Inf
  uint32_t lsb = (bits >> 16) & 1u;
  return (uint16_t)((bits + 0x7FFFu + lsb) >> 16);
}

// One-pass Adam/AdamW over a flat fp32 shard, templated on the
// loop-invariant mode flags so every instantiation is a branch-free,
// auto-vectorizable stream (the reference reaches the same place with
// hand-written AVX512 intrinsics, csrc/adam/cpu_adam.cpp:309; a modern
// -O3 -mavx2 auto-vectorizer matches it on this memory-bound loop once
// divides are hoisted and the body is branchless).
template <bool GRAD_BF16, bool WD_L2, bool WD_ADAMW, bool EMIT_BF16>
void adam_body(float* __restrict p, const void* __restrict grads,
               float grad_scale, float* __restrict m_, float* __restrict v_,
               uint16_t* __restrict bf16_out, int64_t n, float lr, float b1,
               float b2, float eps, float wd, float bc1, float bc2) {
  const float* __restrict gf = (const float*)grads;
  const uint16_t* __restrict gh = (const uint16_t*)grads;
  const float omb1 = 1.0f - b1, omb2 = 1.0f - b2;
  const float inv_bc1 = 1.0f / bc1, inv_bc2 = 1.0f / bc2;
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = GRAD_BF16 ? bf16_to_f32(gh[i]) : gf[i];
    g *= grad_scale;
    if (WD_L2) g += wd * p[i];
    float m = b1 * m_[i] + omb1 * g;
    float v = b2 * v_[i] + omb2 * g * g;
    m_[i] = m;
    v_[i] = v;
    float update = (m * inv_bc1) / (std::sqrt(v * inv_bc2) + eps);
    if (WD_ADAMW) update += wd * p[i];
    float newp = p[i] - lr * update;
    p[i] = newp;
    if (EMIT_BF16) bf16_out[i] = f32_to_bf16(newp);
  }
}

template <bool GRAD_BF16, bool WD_L2, bool WD_ADAMW>
void adam_emit(float* p, const void* g, float gs, float* m, float* v,
               uint16_t* out, int64_t n, float lr, float b1, float b2,
               float eps, float wd, float bc1, float bc2) {
  if (out)
    adam_body<GRAD_BF16, WD_L2, WD_ADAMW, true>(p, g, gs, m, v, out, n, lr,
                                                b1, b2, eps, wd, bc1, bc2);
  else
    adam_body<GRAD_BF16, WD_L2, WD_ADAMW, false>(p, g, gs, m, v, out, n, lr,
                                                 b1, b2, eps, wd, bc1, bc2);
}

template <bool GRAD_BF16>
void adam_wd(float* p, const void* g, float gs, float* m, float* v,
             uint16_t* out, int64_t n, float lr, float b1, float b2,
             float eps, float wd, int adamw, float bc1, float bc2) {
  if (wd > 0.0f && !adamw)
    adam_emit<GRAD_BF16, true, false>(p, g, gs, m, v, out, n, lr, b1, b2,
                                      eps, wd, bc1, bc2);
  else if (wd > 0.0f && adamw)
    adam_emit<GRAD_BF16, false, true>(p, g, gs, m, v, out, n, lr, b1, b2,
                                      eps, wd, bc1, bc2);
  else
    adam_emit<GRAD_BF16, false, false>(p, g, gs, m, v, out, n, lr, b1, b2,
                                       eps, wd, bc1, bc2);
}

}  // namespace

extern "C" {

// Fused one-pass step for the ZeRO-Offload hot path: optional bf16 grad
// input (decoded inline), fused unscale/clip multiplier, and optional bf16
// compute-image emission — one memory sweep instead of four (grad convert,
// grad scale, step, image copy).  bf16_out may be null.
void dstpu_adam_step_fused(float* params, const void* grads, int grads_bf16,
                           float grad_scale, float* exp_avg,
                           float* exp_avg_sq, uint16_t* bf16_out, uint64_t n,
                           int64_t step, float lr, float beta1, float beta2,
                           float eps, float weight_decay, int adamw_mode,
                           int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  if (grads_bf16)
    adam_wd<true>(params, grads, grad_scale, exp_avg, exp_avg_sq, bf16_out,
                  (int64_t)n, lr, beta1, beta2, eps, weight_decay, adamw_mode,
                  bc1, bc2);
  else
    adam_wd<false>(params, grads, grad_scale, exp_avg, exp_avg_sq, bf16_out,
                   (int64_t)n, lr, beta1, beta2, eps, weight_decay,
                   adamw_mode, bc1, bc2);
}

// One Adam/AdamW step over a flat shard.  step is the 1-based step count
// AFTER this update (bias correction uses it directly).
void dstpu_adam_step(float* params, const float* grads, float* exp_avg,
                     float* exp_avg_sq, uint64_t n, int64_t step, float lr,
                     float beta1, float beta2, float eps, float weight_decay,
                     int adamw_mode, int bias_correction) {
  dstpu_adam_step_fused(params, grads, /*grads_bf16=*/0, /*grad_scale=*/1.0f,
                        exp_avg, exp_avg_sq, /*bf16_out=*/nullptr, n, step,
                        lr, beta1, beta2, eps, weight_decay, adamw_mode,
                        bias_correction);
}

void dstpu_adagrad_step(float* params, const float* grads, float* sum_sq,
                        uint64_t n, float lr, float eps, float weight_decay) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < (int64_t)n; ++i) {
    float g = grads[i];
    if (weight_decay > 0.0f) g += weight_decay * params[i];
    float s = sum_sq[i] + g * g;
    sum_sq[i] = s;
    params[i] -= lr * g / (std::sqrt(s) + eps);
  }
}

// fp32 → bf16 (round-to-nearest-even), for pushing compute-dtype params back
// to the device.
void dstpu_copy_f32_to_bf16(const float* src, uint16_t* dst, uint64_t n) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < (int64_t)n; ++i) dst[i] = f32_to_bf16(src[i]);
}

}  // extern "C"
