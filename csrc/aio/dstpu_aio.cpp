// dstpu_aio — asynchronous file IO engine for host/disk tensor swapping.
//
// TPU-native analog of the reference's libaio-based async_io op
// (csrc/aio/common/deepspeed_aio_common.cpp, csrc/aio/py_lib/
// deepspeed_py_aio_handle.cpp): a pool of worker threads services a queue of
// read/write requests against O_DIRECT-capable files, with each large request
// split into block_size chunks spread across the pool so a single tensor swap
// saturates the device queue depth.  Instead of pybind11+torch tensors the
// surface is a flat C ABI over raw host buffers (ctypes-friendly), since the
// JAX side hands us numpy-owned memory.
//
// Semantics mirror the reference handle API:
//   create(block_size, queue_depth, num_threads) -> handle
//   async_pread/async_pwrite -> request id (chunked + enqueued)
//   wait(handle)             -> number of completed requests since last wait
//   sync_pread/sync_pwrite   -> blocking convenience wrappers
//
// Errors: each request records errno; wait() returns -errno of the first
// failed chunk, mirroring the reference's validate_aio_operation behavior.

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

struct Chunk {
  int fd;
  bool write;
  char* buf;
  size_t nbytes;
  off_t offset;
};

struct Request {
  std::atomic<int> pending{0};
  std::atomic<int> error{0};
  int fd = -1;  // owned; closed on completion of all chunks
};

struct Task {
  Chunk chunk;
  std::shared_ptr<Request> req;
};

class AioEngine {
 public:
  AioEngine(size_t block_size, int queue_depth, int num_threads)
      : block_size_(block_size ? block_size : (1u << 20)),
        queue_depth_(queue_depth > 0 ? queue_depth : 32) {
    if (num_threads <= 0) num_threads = 1;
    for (int i = 0; i < num_threads; ++i)
      workers_.emplace_back([this] { Worker(); });
  }

  ~AioEngine() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      shutdown_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int64_t Submit(const char* path, char* buf, size_t nbytes, off_t file_offset,
                 bool write) {
    int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = open(path, flags, 0644);
    if (fd < 0) return -errno;
    auto req = std::make_shared<Request>();
    req->fd = fd;
    size_t nchunks = (nbytes + block_size_ - 1) / block_size_;
    if (nchunks == 0) nchunks = 1;
    req->pending.store(static_cast<int>(nchunks));
    int64_t id;
    {
      std::lock_guard<std::mutex> lk(mu_);
      id = next_id_++;
      inflight_[id] = req;
      for (size_t c = 0; c < nchunks; ++c) {
        size_t off = c * block_size_;
        size_t len = nbytes > off ? std::min(block_size_, nbytes - off) : 0;
        queue_.push_back(Task{
            Chunk{fd, write, buf + off, len,
                  static_cast<off_t>(file_offset + static_cast<off_t>(off))},
            req});
      }
    }
    cv_.notify_all();
    return id;
  }

  // Block until every inflight request completes; return count of completed
  // requests, or -errno of the first failure.
  int WaitAll() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] {
      for (auto& kv : inflight_)
        if (kv.second->pending.load() != 0) return false;
      return true;
    });
    int completed = 0, err = 0;
    for (auto& kv : inflight_) {
      ++completed;
      if (!err) err = kv.second->error.load();
    }
    inflight_.clear();
    return err ? -err : completed;
  }

  // Wait for one request id (sync helpers); returns 0 or -errno.
  int Wait(int64_t id) {
    std::shared_ptr<Request> req;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = inflight_.find(id);
      if (it == inflight_.end()) return 0;
      req = it->second;
    }
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&req] { return req->pending.load() == 0; });
    inflight_.erase(id);
    int err = req->error.load();
    return err ? -err : 0;
  }

  size_t block_size() const { return block_size_; }
  int queue_depth() const { return queue_depth_; }
  int num_threads() const { return static_cast<int>(workers_.size()); }

 private:
  void Worker() {
    for (;;) {
      Task task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return shutdown_ || !queue_.empty(); });
        if (shutdown_ && queue_.empty()) return;
        task = queue_.front();
        queue_.pop_front();
      }
      RunChunk(task);
    }
  }

  void RunChunk(Task& task) {
    Chunk& c = task.chunk;
    size_t done = 0;
    int err = 0;
    while (done < c.nbytes) {
      ssize_t n = c.write ? pwrite(c.fd, c.buf + done, c.nbytes - done,
                                   c.offset + static_cast<off_t>(done))
                          : pread(c.fd, c.buf + done, c.nbytes - done,
                                  c.offset + static_cast<off_t>(done));
      if (n < 0) {
        if (errno == EINTR) continue;
        err = errno;
        break;
      }
      if (n == 0) {  // short file on read
        err = EIO;
        break;
      }
      done += static_cast<size_t>(n);
    }
    if (err) {
      int expected = 0;
      task.req->error.compare_exchange_strong(expected, err);
    }
    if (task.req->pending.fetch_sub(1) == 1) {
      close(task.req->fd);
      std::lock_guard<std::mutex> lk(mu_);
      done_cv_.notify_all();
    }
  }

  const size_t block_size_;
  const int queue_depth_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  std::deque<Task> queue_;
  std::unordered_map<int64_t, std::shared_ptr<Request>> inflight_;
  int64_t next_id_ = 1;
  bool shutdown_ = false;
};

}  // namespace

extern "C" {

void* dstpu_aio_create(uint64_t block_size, int queue_depth, int num_threads) {
  return new AioEngine(block_size, queue_depth, num_threads);
}

void dstpu_aio_destroy(void* h) { delete static_cast<AioEngine*>(h); }

int64_t dstpu_aio_pread(void* h, const char* path, void* buf, uint64_t nbytes,
                        uint64_t offset) {
  return static_cast<AioEngine*>(h)->Submit(path, static_cast<char*>(buf),
                                            nbytes, (off_t)offset, false);
}

int64_t dstpu_aio_pwrite(void* h, const char* path, void* buf, uint64_t nbytes,
                         uint64_t offset) {
  return static_cast<AioEngine*>(h)->Submit(path, static_cast<char*>(buf),
                                            nbytes, (off_t)offset, true);
}

int dstpu_aio_wait(void* h, int64_t req_id) {
  return static_cast<AioEngine*>(h)->Wait(req_id);
}

int dstpu_aio_wait_all(void* h) { return static_cast<AioEngine*>(h)->WaitAll(); }

int dstpu_aio_sync_pread(void* h, const char* path, void* buf, uint64_t nbytes,
                         uint64_t offset) {
  AioEngine* e = static_cast<AioEngine*>(h);
  int64_t id = e->Submit(path, static_cast<char*>(buf), nbytes, (off_t)offset,
                         false);
  if (id < 0) return static_cast<int>(id);
  return e->Wait(id);
}

int dstpu_aio_sync_pwrite(void* h, const char* path, void* buf, uint64_t nbytes,
                          uint64_t offset) {
  AioEngine* e = static_cast<AioEngine*>(h);
  int64_t id = e->Submit(path, static_cast<char*>(buf), nbytes, (off_t)offset,
                         true);
  if (id < 0) return static_cast<int>(id);
  return e->Wait(id);
}

uint64_t dstpu_aio_block_size(void* h) {
  return static_cast<AioEngine*>(h)->block_size();
}
int dstpu_aio_queue_depth(void* h) {
  return static_cast<AioEngine*>(h)->queue_depth();
}
int dstpu_aio_thread_count(void* h) {
  return static_cast<AioEngine*>(h)->num_threads();
}

}  // extern "C"
